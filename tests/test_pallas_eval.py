"""Pallas point-location kernel vs the pure-JAX evaluator (interpret mode:
the kernel is exercised on CPU; on TPU the same code compiles via Mosaic)."""

import jax.numpy as jnp
import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import evaluator, export, pallas_eval
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def built():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_depth=20)
    res = build_partition(prob, cfg)
    table = export.export_leaves(res.tree)
    return prob, res, table


def _synthetic_table(rng, L=40, p=2):
    """A tiny LeafTable built directly from random simplices -- no
    partition build, so this smoke stays tier-1-cheap even if the
    build-backed module fixture ever migrates to the slow tier.  CPU
    CI must always exercise at least one REAL Pallas lowering path
    end-to-end (interpret mode; the same code Mosaic-compiles on
    TPU)."""
    from explicit_hybrid_mpc_tpu.partition import geometry

    base = np.vstack([np.zeros(p), np.eye(p)])  # unit corner simplex
    side = int(np.ceil(np.sqrt(L)))
    bary, U, V = [], [], []
    for i in range(L):
        # Disjoint cells on a unit grid: each simplex is uniquely the
        # best container of its own centroid, so location is exact and
        # the f32 kernel must agree with the f64 reference on ids.
        off = np.array([i % side, i // side], dtype=float)[:p]
        verts = 0.8 * base + off + 0.1 * rng.uniform(size=p)
        bary.append(geometry.barycentric_matrix(verts))
        U.append(rng.normal(size=(p + 1, 1)))
        V.append(np.abs(rng.normal(size=p + 1)))
    return export.LeafTable(
        bary_M=np.stack(bary), U=np.stack(U), V=np.stack(V),
        delta=np.zeros(L, dtype=np.int64),
        node_id=np.arange(L, dtype=np.int64))


def test_locate_smoke_synthetic_vs_f64_evaluator(rng):
    """Tier-1 interpret-mode smoke: the Pallas locate kernel against
    the f64 pure-JAX evaluator on a synthetic table, no build."""
    table = _synthetic_table(rng)
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    # Query AT the simplex centroids: every query is inside its own
    # leaf, so the reference argmax is well-separated and the f32
    # kernel must agree on ids, not just values.
    cents = np.stack([np.linalg.inv(table.bary_M[i])[:-1, :].mean(axis=1)
                      for i in range(table.n_leaves)])
    ref = evaluator.evaluate(dev, jnp.asarray(cents))
    out = pallas_eval.evaluate(pt, dev, jnp.asarray(cents),
                               interpret=True)
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.cost),
                               np.asarray(ref.cost), rtol=1e-5,
                               atol=1e-5)
    assert np.array_equal(np.asarray(out.leaf), np.asarray(ref.leaf))
    assert bool(np.all(np.asarray(out.inside)))


def test_stage_pallas_padding(built):
    _, _, table = built
    pt = pallas_eval.stage_pallas(table)
    PV, K, Lpad = pt.bary_T.shape
    assert pt.n_leaves == table.n_leaves
    assert Lpad % 128 == 0 and Lpad >= table.n_leaves
    assert PV >= table.bary_M.shape[1] and K % 8 == 0


def test_locate_matches_reference(built, rng):
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub,
                         size=(200, prob.n_theta))
    ref = evaluator.evaluate(dev, jnp.asarray(thetas))
    leaf, score = pallas_eval.locate(pt, jnp.asarray(thetas), interpret=True)
    # f32 location may pick the twin leaf at a shared facet; the
    # interpolated VALUES must agree, the ids mostly do.
    same = np.asarray(leaf) == np.asarray(ref.leaf)
    assert same.mean() > 0.95
    out = pallas_eval.evaluate(pt, dev, jnp.asarray(thetas), interpret=True)
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.cost), np.asarray(ref.cost),
                               rtol=1e-4, atol=1e-4)
    assert bool(np.all(np.asarray(out.inside)))


def test_locate_outside(built):
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    out = pallas_eval.evaluate(
        pt, dev, jnp.asarray([[10.0, 10.0]]), interpret=True)
    assert not bool(out.inside[0])


def test_locate_many_query_tiles(built, rng):
    """Queries spanning several 128-row tiles (exercises the query grid)."""
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub,
                         size=(300, prob.n_theta))
    ref = evaluator.evaluate(dev, jnp.asarray(thetas))
    out = pallas_eval.evaluate(pt, dev, jnp.asarray(thetas), interpret=True)
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               atol=1e-4)
