"""Mesh-sharded oracle tests on the 8-virtual-CPU-device mesh.

Validates SURVEY.md section 6.8's build obligation: the frontier solve
batch sharded with shard_map over a (batch, delta) mesh must produce
bit-identical decisions to the single-device path (region-count parity
requires it).
"""

import dataclasses

import numpy as np
import pytest

import jax

from explicit_hybrid_mpc_tpu.oracle import oracle as omod
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle, to_device
from explicit_hybrid_mpc_tpu.parallel import MeshSolver, make_mesh
from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import make


def _synthetic_hybrid(nd=4, nz=3, nc=5, nt=2, seed=0):
    """Random PD mp-QP family with nd commutations (no MPC semantics)."""
    r = np.random.default_rng(seed)

    def slice_(i):
        B = r.normal(size=(nz, nz))
        H = B @ B.T + nz * np.eye(nz)
        G = r.normal(size=(nc, nz))
        # b = w + S theta with w > 0 keeps z=0 feasible for small theta.
        return base.CondensedSlice(
            H=H, f=r.normal(size=nz), F=r.normal(size=(nz, nt)),
            G=G, w=np.abs(r.normal(size=nc)) + 1.0,
            S=0.1 * r.normal(size=(nc, nt)),
            Y=np.eye(nt) * (0.5 + i), pvec=r.normal(size=nt) * 0.1,
            cconst=0.1 * i, u_map=np.eye(1, nz))

    can = base.stack_slices([slice_(i) for i in range(nd)],
                            deltas=np.arange(nd)[:, None])
    return can


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_matches_dense(mesh_shape):
    can = _synthetic_hybrid()
    prob = to_device(can)
    thetas = np.random.default_rng(7).normal(size=(16, 2)) * 0.5

    dense = omod._solve_points_all_deltas(prob, jax.numpy.asarray(thetas), 30)
    mesh = make_mesh(mesh_shape)
    solver = MeshSolver(prob, mesh, n_iter=30)
    sharded = solver(thetas)

    names = ("V", "conv", "feas", "grad", "u0", "z", "Vstar", "dstar")
    for name, a, b in zip(names, dense, sharded):
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype == bool or np.issubdtype(a.dtype, np.integer):
            np.testing.assert_array_equal(a, b, err_msg=name)
        else:
            mask = np.isfinite(a)
            np.testing.assert_array_equal(mask, np.isfinite(b), err_msg=name)
            np.testing.assert_allclose(a[mask], b[mask], rtol=1e-9,
                                       atol=1e-9, err_msg=name)


def test_delta_padding_mesh():
    """nd=3 on a delta-axis-2 mesh: padded slice must not leak into
    results."""
    can = _synthetic_hybrid(nd=3)
    prob = to_device(can)
    thetas = np.random.default_rng(3).normal(size=(8, 2)) * 0.5
    dense = omod._solve_points_all_deltas(prob, jax.numpy.asarray(thetas), 30)
    solver = MeshSolver(prob, make_mesh((4, 2)), n_iter=30)
    sharded = solver(thetas)
    np.testing.assert_array_equal(np.asarray(dense[7]), sharded[7])  # dstar
    a, b = np.asarray(dense[6]), np.asarray(sharded[6])              # Vstar
    np.testing.assert_allclose(a[np.isfinite(a)], b[np.isfinite(b)],
                               rtol=1e-9)
    assert sharded[0].shape == (8, 3)  # delta padding removed


def test_oracle_mesh_backend_parity():
    """Full Oracle on a mesh vs single-device on a real problem."""
    problem = make("double_integrator")
    o_plain = Oracle(problem, backend="cpu")
    o_mesh = Oracle(problem, backend="cpu", mesh=make_mesh((8, 1)))
    thetas = np.random.default_rng(11).uniform(-2, 2, size=(13, 2))
    a = o_plain.solve_vertices(thetas)
    b = o_mesh.solve_vertices(thetas)
    np.testing.assert_array_equal(a.dstar, b.dstar)
    np.testing.assert_allclose(a.Vstar, b.Vstar, rtol=1e-9)
    np.testing.assert_allclose(a.u0, b.u0, rtol=1e-8, atol=1e-10)
