import math

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.partition import geometry as g


def test_kuhn_covers_box(rng):
    for p in (1, 2, 3, 4):
        lb, ub = -np.ones(p), 2 * np.ones(p)
        T = g.kuhn_triangulation(lb, ub)
        assert T.shape == (math.factorial(p), p + 1, p)
        vol = sum(g.simplex_volume(V) for V in T)
        assert np.isclose(vol, 3.0 ** p)
        # Random points lie in >= 1 simplex; interior points in exactly 1.
        pts = rng.uniform(lb, ub, size=(50, p))
        for x in pts:
            hits = sum(g.contains(V, x, tol=1e-12) for V in T)
            assert hits >= 1


def test_barycentric_roundtrip(rng):
    V = rng.normal(size=(4, 3))
    lam = rng.dirichlet(np.ones(4))
    theta = lam @ V
    lam2 = g.barycentric(V, theta)
    np.testing.assert_allclose(lam, lam2, atol=1e-10)
    assert g.contains(V, theta)
    assert not g.contains(V, V.mean(axis=0) + 100.0)


def test_bisect_preserves_volume(rng):
    V = rng.normal(size=(5, 4))
    left, right, i, j, mid = g.bisect(V)
    np.testing.assert_allclose(mid, 0.5 * (V[i] + V[j]))
    assert np.isclose(g.simplex_volume(left) + g.simplex_volume(right),
                      g.simplex_volume(V))
    # Children partition the parent: sampled interior points fall in one.
    for _ in range(20):
        lam = rng.dirichlet(np.ones(5))
        x = lam @ V
        assert g.contains(left, x, 1e-9) or g.contains(right, x, 1e-9)


def test_longest_edge_deterministic():
    V = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    # Edges (0,1) and (0,2) tie at length 1; (1,2) is longest (sqrt 2).
    assert g.longest_edge(V) == (1, 2)
    # Equilateral-ish tie: lexicographic first.
    V2 = np.array([[0.0, 0.0], [1.0, 0.0], [0.5, np.sqrt(3) / 2]])
    assert g.longest_edge(V2) == (0, 1)


def test_longest_edge_tiny_scale():
    """Regression: the tie-break margin must be RELATIVE.  At deep-tree
    scales every squared edge length is < 1e-10 and an absolute 1e-15
    margin would call genuinely longer edges 'ties', silently replacing
    longest-edge selection with lexicographic-first."""
    s = 1e-8
    V = np.array([[0.0, 0.0], [2 * s, 0.0], [0.0, 1 * s]])
    # squared lengths: (0,1)=4s^2, (0,2)=1s^2, (1,2)=5s^2 -> longest (1,2).
    assert g.longest_edge(V) == (1, 2)
    # Exact ties still break lexicographic-first at tiny scale: edges
    # (0,1) and (0,2) tie at s^2, (1,2) is the unique longest (2 s^2).
    V2 = s * np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    assert g.longest_edge(V2) == (1, 2)
    # Degenerate all-tied case (equilateral at tiny scale): the
    # lexicographically first pair wins, deterministically.
    V3 = s * np.array([[0.0, 0.0], [1.0, 0.0], [0.5, np.sqrt(3) / 2]])
    assert g.longest_edge(V3) == (0, 1)


def test_deep_bisection_stays_shape_regular():
    """Rivara longest-edge bisection keeps the aspect ratio bounded; with
    the absolute-margin bug the selected edge stops being the longest
    below ~1e-6 edge lengths and regularity degrades."""
    V = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    for _ in range(100):
        left, _right, i, j, _mid = g.bisect(V)
        # The split edge is (within relative tolerance) a true longest.
        d2 = [float(np.dot(V[a] - V[b], V[a] - V[b]))
              for a in range(3) for b in range(a + 1, 3)]
        split = float(np.dot(V[i] - V[j], V[i] - V[j]))
        assert split >= max(d2) * (1 - 1e-9)
        V = left
    edges = [np.linalg.norm(V[a] - V[b])
             for a in range(3) for b in range(a + 1, 3)]
    assert max(edges) / min(edges) < 10.0  # bounded aspect ratio
    assert max(edges) < 1e-14              # genuinely deep


def test_kuhn_rejects_high_dim():
    with pytest.raises(ValueError):
        g.kuhn_triangulation(-np.ones(9), np.ones(9))


def test_tree_columnar_roundtrip_and_legacy(tmp_path):
    """Columnar tree (r5): O(1) counters, pickle round-trip, and
    transparent loading of the pre-columnar list-of-objects layout
    (every r1-r4 checkpoint and .tree.pkl artifact)."""
    import pickle

    from explicit_hybrid_mpc_tpu.partition.tree import (LeafData, NO_CHILD,
                                                        Tree)

    from explicit_hybrid_mpc_tpu.partition import geometry as geo

    t = Tree(p=2, n_u=1)
    V = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    r = t.add_root(V)
    lv, rv, ei, ej, _mid = geo.bisect(V)
    li, ri = t.split(r, lv, rv, (ei, ej))
    t.set_leaf(li, LeafData(delta_idx=3, vertex_inputs=np.ones((3, 1)),
                            vertex_costs=np.arange(3.0),
                            vertex_z=np.full((3, 4), 2.0)))
    t.set_leaf(ri, LeafData(delta_idx=1, vertex_inputs=np.zeros((3, 1)),
                            vertex_costs=np.ones(3), certified=False,
                            semi_explicit=True))
    assert len(t) == 3 and t.n_regions() == 2 and t.max_depth() == 1
    assert t.roots() == [r] and t.leaves() == [li, ri]
    assert t.leaf_data[r] is None
    assert t.leaf_data[li].delta_idx == 3
    np.testing.assert_array_equal(t.leaf_data[li].vertex_z,
                                  np.full((3, 4), 2.0))
    assert t.leaf_data[ri].semi_explicit and not t.leaf_data[ri].certified
    assert t.leaf_data[ri].vertex_z is None
    # Round-trip through the columnar pickle format.
    path = str(tmp_path / "t.pkl")
    t.save(path)
    t2 = Tree.load(path)
    assert (len(t2), t2.n_regions(), t2.max_depth()) == (3, 2, 1)
    np.testing.assert_array_equal(t2.vertices, t.vertices)
    np.testing.assert_array_equal(t2.children, t.children)
    assert t2.leaf_data[li].delta_idx == 3
    # Legacy layout: simulate an old pickle's instance __dict__.
    legacy = Tree.__new__(Tree)
    legacy.__setstate__({
        "p": 2, "n_u": 1,
        "vertices": [np.asarray(t.vertices[i]) for i in range(3)],
        "parent": [-1, 0, 0],
        "children": [(1, 2), (NO_CHILD, NO_CHILD), (NO_CHILD, NO_CHILD)],
        "depth": [0, 1, 1],
        "split_edge": [(0, 1), (-1, -1), (-1, -1)],
        "leaf_data": [None, t.leaf_data[li], t.leaf_data[ri]],
    })
    assert (len(legacy), legacy.n_regions()) == (3, 2)
    assert legacy.is_leaf(1) and not legacy.is_leaf(0)
    assert legacy.leaf_data[2].semi_explicit
    np.testing.assert_array_equal(legacy.leaf_data[1].vertex_z,
                                  np.full((3, 4), 2.0))


def test_barycentric_matrices_match_scalar():
    """Batched export path (r5): one batched inverse must reproduce the
    per-leaf barycentric_matrix exactly (same np.linalg kernel)."""
    from explicit_hybrid_mpc_tpu.partition import geometry as geo

    rng = np.random.default_rng(5)
    for p in (1, 2, 4, 6):
        Vs = rng.uniform(-2, 2, size=(17, p + 1, p))
        # Keep simplices nondegenerate: nudge towards identity corners.
        Vs += np.eye(p + 1, p)[None] * 3.0
        B = geo.barycentric_matrices(Vs, chunk=5)  # exercise chunking
        for i in range(Vs.shape[0]):
            np.testing.assert_allclose(
                B[i], geo.barycentric_matrix(Vs[i]), rtol=1e-12, atol=1e-12)


def test_longest_edge_einsum_matches_dot_tiebreak(rng):
    """ADVICE r5: longest_edge computes squared lengths via np.einsum;
    the pre-r5 code used per-pair np.dot.  Last-ulp differences between
    the two summation paths could flip the relative-margin tie-break
    and silently change which edge deep builds split.  Pin einsum/dot
    selection equality over random simplices, Kuhn roots, and their
    deep bisection orbits at every tier-1 problem dimension."""

    def dot_select(V):
        n = V.shape[0]
        best = (-1.0, 0, 1)
        for i in range(n):
            for j in range(i + 1, n):
                d = float(np.dot(V[i] - V[j], V[i] - V[j]))
                if d > best[0] * (1.0 + 1e-12):
                    best = (d, i, j)
        return best[1], best[2]

    for p in (1, 2, 4, 6):
        sims = [rng.uniform(-1, 1, size=(p + 1, p)) for _ in range(20)]
        sims += list(g.kuhn_triangulation(np.zeros(p), np.ones(p))[:6])
        for V in sims:
            for _ in range(30):  # bisection orbit: where ties live
                sel = g.longest_edge(V)
                assert sel == dot_select(V), (p, V)
                left, _r, _i, _j, _m = g.bisect(V)
                V = left


def test_split_hyperplanes_batch_matches_scalar(rng):
    """geometry.split_hyperplanes (the split-time/batched-export shared
    routine) row-for-row against the scalar reference in
    online.descent._split_hyperplane."""
    from explicit_hybrid_mpc_tpu.online.descent import _split_hyperplane

    for p in (1, 2, 4, 6):
        Vs, ijs = [], []
        for _ in range(12):
            V = rng.uniform(-1, 1, size=(p + 1, p)) + 2 * np.eye(p + 1, p)
            Vs.append(V)
            ijs.append(g.longest_edge(V))
        w, c = g.split_hyperplanes(np.stack(Vs), np.asarray(ijs))
        for k, (V, ij) in enumerate(zip(Vs, ijs)):
            ws, cs = _split_hyperplane(V, *ij)
            np.testing.assert_allclose(w[k], ws, rtol=1e-12, atol=1e-12)
            np.testing.assert_allclose(c[k], cs, rtol=1e-12, atol=1e-12)
            # Orientation: negative side holds the kept-left vertex.
            assert w[k] @ V[ij[0]] <= c[k] + 1e-12


def test_kuhn_root_locator_matches_brute(rng):
    """Analytic Kuhn root location == brute min-barycentric argmax over
    the triangulation for every in-box query OFF the split planes (same
    first-max tie-break, including repeated-coordinate face ties within
    a sub-box).  Queries EXACTLY ON a split plane are a genuine exact
    tie whose brute winner is decided by last-ulp inverse noise; there
    the router must still name a CONTAINING root (its margin ties the
    brute winner's at ~0), which is all value parity needs."""
    for p, splits in ((2, None), (3, None), (2, {0: [0.25], 1: [-0.5]}),
                      (4, {2: [0.0]})):
        lb, ub = -np.ones(p), np.ones(p)
        roots = g.box_triangulation(lb, ub, splits)
        loc = g.kuhn_root_locator(lb, ub, splits)
        M = np.stack([g.barycentric_matrix(V) for V in roots])
        thetas = rng.uniform(lb, ub, size=(200, p))
        # In-sub-box face ties: repeated coordinates.
        thetas[:20, 1] = thetas[:20, 0]
        on_plane = np.zeros(200, dtype=bool)
        k = 20
        for axis, values in sorted((splits or {}).items()):
            for v in values:
                thetas[k:k + 10, axis] = v
                on_plane[k:k + 10] = True
                k += 10
        th1 = np.concatenate([thetas, np.ones((200, 1))], axis=1)
        lam = np.einsum("rij,bj->bri", M, th1)
        margins = np.min(lam, axis=-1)
        brute = np.argmax(margins, axis=-1)
        mine = loc(thetas)
        np.testing.assert_array_equal(mine[~on_plane], brute[~on_plane])
        # On-plane: containment within fp noise, and the margin ties
        # the brute winner's.
        picked = margins[np.arange(200), mine]
        best = margins[np.arange(200), brute]
        assert np.all(picked[on_plane] >= -1e-12)
        np.testing.assert_allclose(picked[on_plane], best[on_plane],
                                   atol=1e-12)
