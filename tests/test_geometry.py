import math

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.partition import geometry as g


def test_kuhn_covers_box(rng):
    for p in (1, 2, 3, 4):
        lb, ub = -np.ones(p), 2 * np.ones(p)
        T = g.kuhn_triangulation(lb, ub)
        assert T.shape == (math.factorial(p), p + 1, p)
        vol = sum(g.simplex_volume(V) for V in T)
        assert np.isclose(vol, 3.0 ** p)
        # Random points lie in >= 1 simplex; interior points in exactly 1.
        pts = rng.uniform(lb, ub, size=(50, p))
        for x in pts:
            hits = sum(g.contains(V, x, tol=1e-12) for V in T)
            assert hits >= 1


def test_barycentric_roundtrip(rng):
    V = rng.normal(size=(4, 3))
    lam = rng.dirichlet(np.ones(4))
    theta = lam @ V
    lam2 = g.barycentric(V, theta)
    np.testing.assert_allclose(lam, lam2, atol=1e-10)
    assert g.contains(V, theta)
    assert not g.contains(V, V.mean(axis=0) + 100.0)


def test_bisect_preserves_volume(rng):
    V = rng.normal(size=(5, 4))
    left, right, i, j, mid = g.bisect(V)
    np.testing.assert_allclose(mid, 0.5 * (V[i] + V[j]))
    assert np.isclose(g.simplex_volume(left) + g.simplex_volume(right),
                      g.simplex_volume(V))
    # Children partition the parent: sampled interior points fall in one.
    for _ in range(20):
        lam = rng.dirichlet(np.ones(5))
        x = lam @ V
        assert g.contains(left, x, 1e-9) or g.contains(right, x, 1e-9)


def test_longest_edge_deterministic():
    V = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    # Edges (0,1) and (0,2) tie at length 1; (1,2) is longest (sqrt 2).
    assert g.longest_edge(V) == (1, 2)
    # Equilateral-ish tie: lexicographic first.
    V2 = np.array([[0.0, 0.0], [1.0, 0.0], [0.5, np.sqrt(3) / 2]])
    assert g.longest_edge(V2) == (0, 1)


def test_kuhn_rejects_high_dim():
    with pytest.raises(ValueError):
        g.kuhn_triangulation(-np.ones(9), np.ones(9))
