"""Bounded async build pipeline (partition/pipeline.py): bit-parity at
depth >= 2 with speculation + dedup, cross-batch solve coalescing,
checkpoint/resume quiescence, mesh parity, and the new config/oracle
knobs."""

import collections
import os

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        build_partition)
from explicit_hybrid_mpc_tpu.problems.registry import make

EPS = 0.35


def _tree_signature(res):
    """Node-for-node structural identity: every node's vertex matrix
    (bitwise -- bisection arithmetic is exact), every leaf's chosen
    commutation and certification status, and the region/node counts.
    Leaf PAYLOAD floats are deliberately excluded: a solve served from
    a different pow-2 device bucket is a different XLA executable and
    may differ in the final ulp (the same caveat the legacy prefetch
    and the warm-start donors carry); the parity contract is the tree,
    not the last bit of V."""
    tree = res.tree
    leaves = tree.converged_leaves()
    return (res.stats["regions"], res.stats["tree_nodes"],
            res.stats["uncertified"], res.stats["semi_explicit"],
            tuple(tree.vertices[n].tobytes() for n in range(len(tree))),
            tuple(tree.leaf_data[n].delta_idx for n in leaves),
            tuple(bool(tree.leaf_data[n].certified) for n in leaves))


def _build(prob, name, **kw):
    cfg = PartitionConfig(problem=name, eps_a=kw.pop("eps_a", EPS),
                          backend="cpu",
                          batch_simplices=kw.pop("batch_simplices", 16),
                          max_depth=kw.pop("max_depth", 20), **kw)
    return build_partition(prob, cfg, Oracle(prob, backend="cpu"))


def test_pipeline_bit_parity_with_speculation():
    """Acceptance: pipeline_depth >= 2 + speculation + dedup produce a
    BIT-IDENTICAL tree (same region count, node-for-node vertices and
    leaf payloads) vs the synchronous reference."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    ref = _build(prob, "double_integrator", prefetch_solves=False)
    pipe = _build(prob, "double_integrator", pipeline_depth=3,
                  speculate=True)
    assert _tree_signature(ref) == _tree_signature(pipe)
    assert pipe.stats["pipelined_steps"] > 0
    assert pipe.stats["pipeline_fill_frac"] > 0


def test_pipeline_bit_parity_hybrid_warm(monkeypatch):
    """Same acceptance on a hybrid problem exercising masked solves,
    warm-start donors, stage-2 programs, and speculation on the
    mixed-feasibility boundary.  The idle-device gate is lifted so
    speculation actually dispatches on this always-busy CPU host."""
    from explicit_hybrid_mpc_tpu.partition.pipeline import BuildPipeline

    monkeypatch.setattr(BuildPipeline, "SPEC_DEVICE_FRAC_MAX", 2.0)
    prob = make("inverted_pendulum", N=3)
    out = {}
    for key, kw in (("sync", dict(prefetch_solves=False)),
                    ("pipe", dict(pipeline_depth=3, speculate=True))):
        cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                              backend="cpu", batch_simplices=64,
                              max_depth=12, **kw)
        out[key] = build_partition(prob, cfg,
                                   Oracle(prob, backend="cpu"))
    assert _tree_signature(out["sync"]) == _tree_signature(out["pipe"])
    s = out["pipe"].stats
    # Speculation actually fired on the mode-boundary cells and its
    # economy figures are well-formed.
    assert s["spec_hits"] > 0
    assert 0.0 <= s["spec_hit_rate"] <= 1.0
    assert 0.0 <= s["spec_waste_frac"] < 1.0
    assert s["simplex_solves"] == out["sync"].stats["simplex_solves"]


class _SpyOracle(Oracle):
    """Counts every dispatched-and-waited (vertex, delta) point cell."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.waited = collections.Counter()
        self._pend = {}

    def dispatch_vertices(self, thetas):
        h = super().dispatch_vertices(thetas)
        keys = [geometry.vertex_key(t) for t in np.atleast_2d(thetas)]
        self._pend[id(h)] = [(k, d) for k in keys
                             for d in range(self.can.n_delta)]
        return h

    def wait_vertices(self, h):
        for c in self._pend.pop(id(h), ()):
            self.waited[c] += 1
        return super().wait_vertices(h)

    def dispatch_pairs(self, thetas, ds, warm=None):
        h = (super().dispatch_pairs(thetas, ds, warm=warm)
             if warm is not None else super().dispatch_pairs(thetas, ds))
        self._pend[id(h)] = [
            (geometry.vertex_key(t), int(d))
            for t, d in zip(np.atleast_2d(thetas), np.asarray(ds))]
        return h

    def wait_pairs_full(self, h):
        for c in self._pend.pop(id(h), ()):
            self.waited[c] += 1
        return super().wait_pairs_full(h)


def test_dedup_coalesces_and_fans_out():
    """Cross-batch dedup: duplicate (vertex, delta) requests across the
    in-flight window collapse into ONE device solve whose rows serve
    every requester.  With speculation off the pipelined build must
    therefore wait each cell exactly as often as the synchronous build
    does (the old prefetch re-solved batch-boundary midpoints), while
    producing the identical tree -- i.e. every requester received the
    coalesced solve's rows."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    waited = {}
    sigs = {}
    for key, kw in (("sync", dict(prefetch_solves=False)),
                    ("pipe", dict(pipeline_depth=3, speculate=False))):
        cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                              backend="cpu", batch_simplices=16,
                              max_depth=20, **kw)
        o = _SpyOracle(prob, backend="cpu")
        res = build_partition(prob, cfg, o)
        waited[key] = o.waited
        sigs[key] = _tree_signature(res)
    assert sigs["sync"] == sigs["pipe"]
    # Exactly the synchronous multiset of waited cells: nothing solved
    # twice that the serial build solves once.
    assert waited["pipe"] == waited["sync"]


def test_resume_mid_pipeline():
    """Checkpointing with claims + speculation in flight must cancel
    them (quiescent snapshot) so a resumed build re-dispatches nothing
    already committed and finishes with the identical tree."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    ref = _build(prob, "double_integrator", prefetch_solves=False)

    def engine():
        cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                              backend="cpu", batch_simplices=16,
                              max_depth=20, pipeline_depth=3,
                              speculate=True)
        return FrontierEngine(prob, Oracle(prob, backend="cpu"), cfg)

    eng = engine()
    for _ in range(6):
        eng.step()
    assert eng._pipe.in_flight > 0  # the lookahead is genuinely armed
    ckpt = os.path.join(os.environ.get("PYTEST_TMP", "/tmp"),
                        "pipe_resume.pkl")
    eng.save_checkpoint(ckpt)
    # The satellite bugfix: a snapshot is only taken at a quiescent
    # boundary -- nothing in flight survives into (or out of) it.
    assert eng._pipe.in_flight == 0
    res_a = eng.run()                       # original finishes
    eng2 = FrontierEngine.resume(ckpt, prob, Oracle(prob, backend="cpu"))
    assert eng2._pipe.in_flight == 0
    res_b = eng2.run()                      # resumed finishes
    assert _tree_signature(ref) == _tree_signature(res_a)
    assert _tree_signature(ref) == _tree_signature(res_b)
    # No re-dispatch of already-committed work: the resumed session's
    # total solve count equals the straight run's.
    assert res_b.stats["oracle_solves"] == res_a.stats["oracle_solves"]
    os.unlink(ckpt)


def test_pipeline_parity_under_mesh():
    """Acceptance: bit-identical trees under the virtual-device mesh
    too (the mesh path keeps the dense grid route; warm starts and the
    cohort are forced off there)."""
    from explicit_hybrid_mpc_tpu.parallel import make_mesh

    prob = make("double_integrator", N=3, theta_box=1.5)
    out = {}
    for key, kw in (("sync", dict(prefetch_solves=False)),
                    ("pipe", dict(pipeline_depth=2, speculate=True))):
        cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                              backend="cpu", batch_simplices=16,
                              max_depth=16, **kw)
        oracle = Oracle(prob, backend="cpu", mesh=make_mesh((8, 1)))
        out[key] = build_partition(prob, cfg, oracle)
    assert _tree_signature(out["sync"]) == _tree_signature(out["pipe"])


def test_pipeline_obs_metrics_schema():
    """The new pipeline gauges land in the metrics snapshot with the
    documented names (scripts/obs_report.py and the bench read them)."""
    from explicit_hybrid_mpc_tpu import obs as obs_lib

    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=16,
                          max_depth=16, pipeline_depth=2, obs="jsonl")
    handle = obs_lib.Obs("jsonl")
    build_partition(prob, cfg, Oracle(prob, backend="cpu"), obs=handle)
    gauges = handle.metrics.snapshot()["gauges"]
    for name in ("build.pipeline_fill", "build.pipeline_fill_frac",
                 "build.dedup_saved", "build.spec_hit_rate",
                 "build.spec_waste_frac"):
        assert name in gauges, name
    assert 0.0 <= gauges["build.pipeline_fill_frac"] <= 1.0
    assert 0.0 <= gauges["build.spec_waste_frac"] <= 1.0


def test_obs_report_pipeline_block():
    """scripts/obs_report.py renders the pipeline occupancy block from
    a stream's gauges and diff-flags pipeline-economy regressions
    against a bench JSON (like the existing wasted_iter_frac flags)."""
    import importlib
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        obs_report = importlib.import_module("obs_report")
    finally:
        sys.path.pop(0)
    records = [
        {"kind": "event", "name": "build.step", "t": 1.0, "step": 1,
         "regions": 10, "device_frac": 0.4, "pipeline": 2},
        {"kind": "metrics", "counters": {},
         "gauges": {"build.pipeline_fill": 1.0,
                    "build.pipeline_fill_frac": 0.4,
                    "build.dedup_saved": 12.0,
                    "build.spec_hit_rate": 0.3,
                    "build.spec_waste_frac": 0.2},
         "histograms": {}},
    ]
    rep = obs_report.report(records)
    pipe = rep["pipeline"]
    assert pipe["pipeline_fill_frac"] == 0.4
    assert pipe["dedup_saved"] == 12.0
    assert pipe["device_busy_frac"] == 0.4
    assert abs(pipe["host_busy_frac"] - 0.6) < 1e-12
    text = obs_report.render_text(rep, [], None)
    assert "pipeline: fill 0.40" in text
    bench = {"pipeline_fill_frac": 0.668, "spec_hit_rate": 0.58,
             "spec_waste_frac": 0.004}
    flags = obs_report.diff_bench(rep, bench, tol=0.10)
    assert any("pipeline fill" in f for f in flags)
    assert any("speculation hit rate" in f for f in flags)
    assert any("speculation waste" in f for f in flags)


def test_bench_gate_spec_waste_abs_slack():
    """spec_waste_frac gates with an ABSOLUTE slack on top of the
    relative band: speculation volume is timing-gated, so noise-level
    absolute changes on a near-zero reference must not fail CI, while
    a real waste blow-up still does."""
    import importlib
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        bench_gate = importlib.import_module("bench_gate")
    finally:
        sys.path.pop(0)
    hist = [{"source": "a.json", "platform": "cpu", "contended": False,
             "error": None, "spec_waste_frac": 0.004, "value": 300.0}]
    base = {"source": "b.json", "platform": "cpu", "contended": False,
            "error": None, "value": 300.0}
    # +50% relative but only +0.002 absolute: within the slack.
    flags, _ = bench_gate.gate({**base, "spec_waste_frac": 0.006}, hist)
    assert not any("spec_waste_frac" in f for f in flags)
    # A genuine blow-up clears both the relative band and the slack.
    flags, _ = bench_gate.gate({**base, "spec_waste_frac": 0.16}, hist)
    assert any("spec_waste_frac" in f for f in flags)
    # All-zero history (speculation dormant on that platform) must NOT
    # blind the gate: 0 is the healthy reference for slack-bearing
    # ratio metrics, and a blow-up past the slack still flags.
    hist0 = [dict(hist[0], spec_waste_frac=0.0)]
    flags, _ = bench_gate.gate({**base, "spec_waste_frac": 0.01}, hist0)
    assert not any("spec_waste_frac" in f for f in flags)
    flags, _ = bench_gate.gate({**base, "spec_waste_frac": 0.16}, hist0)
    assert any("spec_waste_frac" in f for f in flags)


def test_config_validation():
    with pytest.raises(ValueError):
        PartitionConfig(pipeline_depth=-1)
    with pytest.raises(ValueError):
        PartitionConfig(dedup_window=0)
    with pytest.raises(ValueError):
        PartitionConfig(ipm_phase1_iters_point=0)
    with pytest.raises(ValueError):
        PartitionConfig(ipm_phase1_iters_simplex=0)
    # prefetch_solves=False is the pipeline_depth=0 compat alias.
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", prefetch_solves=False,
                          pipeline_depth=5)
    eng = FrontierEngine(prob, Oracle(prob, backend="cpu"), cfg)
    assert eng._pipe.depth == 0


def test_per_class_phase1_overrides():
    """Oracle-level per-class phase-1 splits: each class override wins
    over the shared phase1_iters, which wins over the auto 2/5 split;
    the CPU twin mirrors them."""
    prob = make("inverted_pendulum", N=3)
    o = Oracle(prob, backend="cpu", two_phase=True, precision="mixed",
               phase1_iters=3, phase1_iters_point=1,
               phase1_iters_simplex=2)
    assert o.point_p1 == 1
    assert o.simplex_p1 == 2
    twin = o.cpu_twin(prob)
    assert twin.point_p1 == o.point_p1
    assert twin.simplex_p1 == o.simplex_p1
    # Shared value applies where no class override is given.
    o2 = Oracle(prob, backend="cpu", two_phase=True, precision="mixed",
                phase1_iters=3, phase1_iters_point=1)
    assert o2.point_p1 == 1
    assert o2.simplex_p1 == min(3, o2.n_iter)
    with pytest.raises(ValueError):
        Oracle(prob, backend="cpu", phase1_iters_point=0)
    # Per-class knobs flow from the config through make_oracle.
    from explicit_hybrid_mpc_tpu.partition.frontier import make_oracle

    cfg = PartitionConfig(problem="inverted_pendulum", backend="cpu",
                          precision="mixed",
                          ipm_phase1_iters_point=1,
                          ipm_phase1_iters_simplex=2)
    o3 = make_oracle(prob, cfg)
    assert o3.point_p1 == 1
    assert o3.simplex_p1 == 2
