"""Unit tests for the obs subsystem: sink, tracer, metrics registry,
the RunLog compatibility shim, the relocated ContentionMonitor, and
scripts/profile_capture.summarize_trace."""

import gzip
import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.obs.host import ContentionMonitor
from explicit_hybrid_mpc_tpu.obs.metrics import (Histogram,
                                                 MetricsRegistry, quantile)
from explicit_hybrid_mpc_tpu.obs.sink import (SCHEMA_VERSION, JsonlSink,
                                              load_jsonl)
from explicit_hybrid_mpc_tpu.utils.logging import RunLog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- sink ------------------------------------------------------------------

def test_sink_coerces_numpy(tmp_path):
    p = str(tmp_path / "s.jsonl")
    with JsonlSink(p) as s:
        s.emit("event", "e", a=np.float32(1.5), b=np.int64(7),
               c=np.arange(3), d=np.bool_(True))
    (rec,) = load_jsonl(p)
    assert rec["a"] == 1.5 and rec["b"] == 7
    assert rec["c"] == [0, 1, 2] and rec["d"] is True


def test_sink_closes_on_exception(tmp_path):
    p = str(tmp_path / "s.jsonl")
    with pytest.raises(RuntimeError):
        with JsonlSink(p) as s:
            s.emit("event", "e")
            raise RuntimeError("boom")
    assert s._fh is None  # handle closed despite the raise
    assert len(load_jsonl(p)) == 1


def test_sink_base_t_monotonic():
    s = JsonlSink(base_t=100.0)
    rec = s.emit("event", "e")
    assert rec["t"] >= 100.0


def test_sink_bounds_memory_but_not_file(tmp_path):
    p = str(tmp_path / "s.jsonl")
    with JsonlSink(p, max_records=3) as s:
        for i in range(5):
            s.emit("event", "e", i=i)
    assert len(s.records) == 3 and s.n_dropped == 2
    assert len(load_jsonl(p)) == 5  # the file keeps everything


def test_sink_thread_safe(tmp_path):
    p = str(tmp_path / "s.jsonl")
    s = JsonlSink(p)

    def worker(k):
        for i in range(50):
            s.emit("event", f"w{k}", i=i)

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s.close()
    recs = load_jsonl(p)
    assert len(recs) == 200 == len(s.records)


def test_sink_tolerant_tail_drops_truncated_final_line(tmp_path):
    """A writer killed mid-record leaves a truncated last line; the
    stream must still parse (crashed runs are when it matters most)."""
    p = str(tmp_path / "t.jsonl")
    with open(p, "w") as f:
        f.write('{"t": 1.0, "kind": "event", "name": "a"}\n')
        f.write('{"t": 2.0, "kind": "ev')  # torn mid-record
    recs = load_jsonl(p)
    assert len(recs) == 1 and recs[0]["name"] == "a"
    with pytest.raises(json.JSONDecodeError):
        load_jsonl(p, tolerant_tail=False)


def test_sink_corrupt_middle_still_raises(tmp_path):
    """Tolerance covers ONLY the final line: garbage mid-file means the
    file is damaged, not merely cut short."""
    p = str(tmp_path / "t.jsonl")
    with open(p, "w") as f:
        f.write('{"t": 1.0, "kind": "ev')  # torn...
        f.write("\n")
        f.write('{"t": 2.0, "kind": "event", "name": "b"}\n')  # ...followed
    with pytest.raises(json.JSONDecodeError):
        load_jsonl(p)


_KILLED_WRITER = """
import os, signal, sys
sys.path.insert(0, {repo!r})
from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink
s = JsonlSink({path!r}, schema_meta=True)
for i in range(10_000):
    s.emit("event", "tick", i=i, payload="x" * 200)
    if i == 50:
        os.kill(os.getpid(), signal.SIGKILL)  # crash mid-stream
"""


def test_sink_survives_sigkilled_writer(tmp_path):
    """Satellite (ISSUE 4): kill a writer mid-stream and the file still
    parses -- per-record flush + tolerant-tail load."""
    p = str(tmp_path / "killed.jsonl")
    proc = subprocess.run(
        [sys.executable, "-c",
         _KILLED_WRITER.format(repo=REPO, path=p)],
        capture_output=True, timeout=120)
    assert proc.returncode == -signal.SIGKILL
    recs = load_jsonl(p)  # must not raise, torn tail or not
    assert recs[0]["name"] == "schema"
    ticks = [r for r in recs if r["name"] == "tick"]
    assert len(ticks) >= 50  # everything up to the kill survived
    assert ticks[-1]["i"] == ticks[0]["i"] + len(ticks) - 1  # no holes


_UNCLOSED_WRITER = """
import sys
sys.path.insert(0, {repo!r})
from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink
s = JsonlSink({path!r}, schema_meta=True)
for i in range(20):
    s.emit("event", "tick", i=i)
raise SystemExit(3)  # exits WITHOUT close(): the atexit hook must flush
"""


def test_sink_atexit_closes_unclosed_writer(tmp_path):
    p = str(tmp_path / "atexit.jsonl")
    proc = subprocess.run(
        [sys.executable, "-c",
         _UNCLOSED_WRITER.format(repo=REPO, path=p)],
        capture_output=True, timeout=120)
    assert proc.returncode == 3
    recs = load_jsonl(p, tolerant_tail=False)  # complete, no torn tail
    assert sum(r["name"] == "tick" for r in recs) == 20


def test_sink_close_unregisters_atexit(tmp_path):
    import atexit

    s = JsonlSink(str(tmp_path / "s.jsonl"))
    s.emit("event", "e")
    s.close()
    # Double close (context manager + atexit ordering) must be safe.
    s.close()
    atexit.unregister(s.close)  # no-op either way; must not raise


def test_sink_tap_sees_every_record(tmp_path):
    seen = []
    s = JsonlSink(str(tmp_path / "s.jsonl"), tap=seen.append)
    s.emit("event", "a", i=1)
    s.emit("span", "b", wall_s=0.1)
    s.close()
    assert [r["name"] for r in seen] == ["a", "b"]


# -- RunLog shim (satellite regressions) -----------------------------------

def test_runlog_numpy_scalars_do_not_crash(tmp_path):
    """json.dumps used to TypeError on numpy fields in the stats dict."""
    p = str(tmp_path / "r.jsonl")
    log = RunLog(p, echo=False)
    log.emit(step=np.int64(3), regions_per_s=np.float32(17.5),
             grad=np.zeros(2))
    log.close()
    (rec,) = load_jsonl(p)
    assert rec["step"] == 3 and rec["regions_per_s"] == 17.5


def test_runlog_is_context_manager(tmp_path):
    p = str(tmp_path / "r.jsonl")
    with pytest.raises(ValueError):
        with RunLog(p, echo=False) as log:
            log.emit(step=1)
            raise ValueError("boom")
    assert log.sink._fh is None
    assert load_jsonl(p)[0]["step"] == 1


def test_runlog_legacy_layout_and_consumers(tmp_path):
    """Flat top-level fields + t, parseable by post.analysis."""
    from explicit_hybrid_mpc_tpu.post import load_runlog, runtime_report

    p = str(tmp_path / "r.jsonl")
    with RunLog(p, echo=False) as log:
        for k in range(3):
            log.emit(step=k + 1, regions=10 * (k + 1), frontier=5,
                     solves=7, step_s=0.1, device_frac=0.5)
        log.emit(done=True, regions=30, steps=3)
    recs = load_runlog(p)
    rep = runtime_report(recs)
    assert rep["n_steps"] == 3
    assert rep["regions_final"] == 30
    assert rep["final_stats"]["regions"] == 30


# -- tracer ----------------------------------------------------------------

def test_tracer_nesting_and_cpu_time(tmp_path):
    o = obs_lib.Obs("jsonl")
    with o.span("outer") as sp:
        sp["extra"] = 42
        with o.span("inner"):
            sum(range(10000))
    recs = o.sink.records
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert inner["depth"] == 1 and inner["parent"] == "outer"
    assert outer["depth"] == 0 and outer["parent"] is None
    assert outer["extra"] == 42
    assert outer["wall_s"] >= inner["wall_s"] >= 0.0
    assert outer["cpu_s"] >= 0.0


def test_span_emitted_even_on_exception():
    o = obs_lib.Obs("jsonl")
    with pytest.raises(RuntimeError):
        with o.span("fails"):
            raise RuntimeError("boom")
    assert any(r["name"] == "fails" for r in o.sink.records)


# -- metrics ---------------------------------------------------------------

def test_histogram_counts_sum_and_weighted_observe():
    h = Histogram()
    h.observe(1e-5, n=10)
    h.observe(1e-3, n=5)
    h.observe(2.0)
    snap = h.snapshot()
    assert sum(snap["counts"]) == snap["count"] == 16
    assert snap["min"] == 1e-5 and snap["max"] == 2.0
    np.testing.assert_allclose(snap["sum"], 10e-5 + 5e-3 + 2.0)


def test_histogram_quantiles_are_sane():
    h = Histogram()
    rng = np.random.default_rng(0)
    vals = 10.0 ** rng.uniform(-6, -3, size=2000)
    for v in vals:
        h.observe(float(v))
    snap = h.snapshot()
    p50 = quantile(snap, 0.5)
    p99 = quantile(snap, 0.99)
    # Log-bucket estimate: within one bucket ratio (10^(1/5)) of truth.
    assert np.quantile(vals, 0.5) / 1.6 <= p50 <= np.quantile(vals, 0.5) * 1.6
    assert p99 >= p50
    assert quantile(snap, 0.0) >= snap["min"]
    assert quantile(snap, 1.0) <= snap["max"] * (1 + 1e-12)
    assert quantile({"count": 0, "bounds": [], "counts": [0],
                     "sum": 0.0, "min": None, "max": None}, 0.5) is None


def test_quantile_empty_histogram_is_none():
    h = Histogram()
    snap = h.snapshot()
    assert snap["min"] is None and snap["max"] is None
    for q in (0.0, 0.5, 1.0):
        assert quantile(snap, q) is None


def test_quantile_single_bucket_mass_is_exact():
    """All mass on one value: min == max clamp the landing bucket, so
    every quantile is exactly that value -- no interpolation smear."""
    h = Histogram()
    h.observe(3.7e-4, n=1000)
    snap = h.snapshot()
    for q in (0.0, 0.25, 0.5, 0.99, 1.0):
        assert quantile(snap, q) == pytest.approx(3.7e-4, rel=1e-12)


def test_quantile_q0_q1_respect_min_max():
    h = Histogram()
    rng = np.random.default_rng(7)
    vals = 10.0 ** rng.uniform(-5, -2, size=500)
    for v in vals:
        h.observe(float(v))
    snap = h.snapshot()
    assert quantile(snap, 0.0) >= snap["min"]
    assert quantile(snap, 1.0) <= snap["max"] * (1 + 1e-12)
    assert quantile(snap, 1.0) >= quantile(snap, 0.0)


def test_quantile_weighted_observe_matches_numpy_reference():
    """observe(v, n=k) must be distribution-identical to k separate
    observes, and the estimate must track np.quantile of the expanded
    sample within one log-bucket ratio (10^(1/5))."""
    rng = np.random.default_rng(21)
    vals = 10.0 ** rng.uniform(-6, -3, size=200)
    weights = rng.integers(1, 50, size=200)
    hw = Histogram()
    hu = Histogram()
    for v, n in zip(vals, weights):
        hw.observe(float(v), n=int(n))
        for _ in range(int(n)):
            hu.observe(float(v))
    sw, su = hw.snapshot(), hu.snapshot()
    assert sw["counts"] == su["counts"] and sw["count"] == su["count"]
    expanded = np.repeat(vals, weights)
    bucket_ratio = 10.0 ** (1.0 / 5.0)
    for q in (0.1, 0.5, 0.9, 0.99):
        est = quantile(sw, q)
        ref = float(np.quantile(expanded, q))
        assert ref / bucket_ratio <= est <= ref * bucket_ratio, (q, est,
                                                                 ref)
        assert est == quantile(su, q)  # weighted == unweighted


def test_quantile_mass_in_underflow_and_overflow_buckets():
    """Values outside the fixed bounds land in the open-ended tail
    cells; min/max clamping keeps the estimates finite and ordered."""
    h = Histogram(bounds=(1e-3, 1e-2, 1e-1))
    h.observe(1e-6, n=10)   # underflow cell
    h.observe(5.0, n=10)    # overflow cell
    snap = h.snapshot()
    lo, hi = quantile(snap, 0.25), quantile(snap, 0.95)
    assert 1e-6 <= lo <= 1e-3
    assert 1e-1 <= hi <= 5.0
    assert quantile(snap, 0.0) >= 1e-6
    assert quantile(snap, 1.0) <= 5.0 * (1 + 1e-12)


def test_snapshot_delta_quantiles_under_concurrent_observe():
    """The serve_bench / obs-slo idiom -- quantiles from the DELTA of
    two cumulative snapshots -- must stay sound while writer threads
    observe() concurrently: every mid-flight snapshot is internally
    consistent (sum(counts) == count) and monotone, deltas are
    non-negative, and the delta-window quantiles track a numpy
    reference over exactly that window's samples within one
    log-bucket ratio (10^(1/5))."""
    h = Histogram()
    n_threads, n_obs = 4, 3000

    def run_phase(lo_exp, hi_exp, seed0):
        recorded = []

        def writer(seed):
            vals = 10.0 ** np.random.default_rng(seed).uniform(
                lo_exp, hi_exp, size=n_obs)
            for v in vals:
                h.observe(float(v))
            recorded.append(vals)

        threads = [threading.Thread(target=writer, args=(seed0 + k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        prev = None
        while any(t.is_alive() for t in threads):
            snap = h.snapshot()
            assert sum(snap["counts"]) == snap["count"]
            if prev is not None:
                assert snap["count"] >= prev["count"]
                assert all(c >= p for c, p in zip(snap["counts"],
                                                 prev["counts"]))
            prev = snap
        for t in threads:
            t.join()
        return np.concatenate(recorded)

    vals1 = run_phase(-6, -3, 100)
    snap1 = h.snapshot()
    # The second phase lands in a DIFFERENT decade band, so a quantile
    # computed from the cumulative histogram would be wrong for the
    # window -- only the delta is right.
    vals2 = run_phase(-4, -1, 200)
    snap2 = h.snapshot()
    assert snap1["count"] == vals1.size
    assert snap2["count"] == vals1.size + vals2.size
    delta_counts = [c - p for c, p in zip(snap2["counts"],
                                          snap1["counts"])]
    assert all(c >= 0 for c in delta_counts)
    assert sum(delta_counts) == vals2.size
    delta = {"bounds": snap2["bounds"], "counts": delta_counts,
             "count": int(sum(delta_counts)),
             "sum": snap2["sum"] - snap1["sum"],
             "min": float(vals2.min()), "max": float(vals2.max())}
    bucket_ratio = 10.0 ** (1.0 / 5.0)
    for q in (0.5, 0.99):
        est = quantile(delta, q)
        ref = float(np.quantile(vals2, q))
        assert ref / bucket_ratio <= est <= ref * bucket_ratio, (q, est,
                                                                 ref)


def test_registry_snapshot_and_summary():
    m = MetricsRegistry()
    m.counter("a.count").inc(3)
    m.counter("a.count").inc()
    m.gauge("a.gauge").set(2.5)
    m.histogram("a.lat_s").observe(0.01, n=4)
    snap = m.snapshot()
    assert snap["counters"]["a.count"] == 4
    assert snap["gauges"]["a.gauge"] == 2.5
    assert snap["histograms"]["a.lat_s"]["count"] == 4
    summ = m.summary()
    row = summ["histograms"]["a.lat_s"]
    assert row["count"] == 4 and row["p50"] > 0 and row["p99"] > 0
    json.dumps(summ)  # JSON-ready


def test_registry_emit_record_shape():
    o = obs_lib.Obs("jsonl")
    o.counter("c").inc()
    o.flush_metrics()
    rec = next(r for r in o.sink.records if r["kind"] == "metrics")
    assert rec["name"] == "snapshot" and rec["counters"]["c"] == 1


# -- Obs facade ------------------------------------------------------------

def test_obs_off_is_noop():
    o = obs_lib.NOOP
    assert not o.enabled and o.sink is None
    with o.span("x") as sp:
        sp["k"] = 1  # shared dict; must not raise
    o.counter("c").inc()
    o.gauge("g").set(1.0)
    o.histogram("h").observe(0.1, n=5)
    o.event("e", a=1)
    o.flush_metrics()
    o.close()  # all no-ops


def test_obs_mode_validation():
    with pytest.raises(ValueError):
        obs_lib.Obs("bogus")
    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    with pytest.raises(ValueError):
        PartitionConfig(obs="bogus")
    cfg = PartitionConfig(obs="jsonl")
    o = obs_lib.from_config(cfg)
    assert o.enabled and o.mode == "jsonl"
    assert obs_lib.from_config(PartitionConfig()) is obs_lib.NOOP


def test_obs_stream_has_schema_header(tmp_path):
    p = str(tmp_path / "o.jsonl")
    with obs_lib.Obs("jsonl", path=p):
        pass
    first = load_jsonl(p)[0]
    assert first["kind"] == "meta" and first["name"] == "schema"
    assert first["version"] == SCHEMA_VERSION


def test_obs_default_handle_roundtrip():
    o = obs_lib.Obs("jsonl")
    try:
        assert obs_lib.set_default(o) is o
        assert obs_lib.default() is o
    finally:
        obs_lib.set_default(None)
    assert obs_lib.default() is obs_lib.NOOP


# -- ContentionMonitor (satellite: fake /proc readers) ---------------------

def test_monitor_competing_frac_arithmetic():
    # 100 busy jiffies total, 40 of them ours -> 60 competing over a
    # 120-jiffy capacity = 0.5.
    assert ContentionMonitor._competing_frac((0, 0), (100, 40), 120.0) \
        == 0.5
    # Clamped to [0, 1].
    assert ContentionMonitor._competing_frac((0, 0), (500, 0), 100.0) == 1.0
    assert ContentionMonitor._competing_frac((0, 0), (10, 50), 100.0) == 0.0


def test_monitor_fake_proc_stat_guest_subtraction(tmp_path):
    """The real file-parsing path, on fixture files: guest/guest_nice
    ticks (already inside user/nice) must come off the busy total."""
    stat = tmp_path / "stat"
    self_stat = tmp_path / "self_stat"
    # user nice system idle iowait irq softirq steal guest guest_nice
    stat.write_text("cpu 100 10 50 900 30 5 5 10 40 2\nrest ignored\n")
    self_stat.write_text(
        "1 (proc name) S " + " ".join(str(i) for i in range(9, 31)) + "\n")
    mon = ContentionMonitor(stat_path=str(stat),
                            self_stat_path=str(self_stat))
    busy, own = mon._jiffies()
    assert busy == 100 + 10 + 50 + 5 + 5 + 10  # guest ticks excluded
    # utime stime cutime cstime = post-comm fields 11..14 = 19 20 21 22
    assert own == 19 + 20 + 21 + 22
    # Advance the files by +100 user jiffies that are ALL guest time
    # (the kernel accounts guest inside user AND in the guest field):
    # the busy delta must count that work exactly ONCE, not twice.
    stat.write_text("cpu 200 10 50 900 30 5 5 10 140 2\n")
    busy2, _ = mon._jiffies()
    assert busy2 - busy == 100


def test_monitor_scripted_reader_and_gauge_folding():
    m = MetricsRegistry()
    seq = [(0, 0), (100, 10), (200, 20), (300, 30), (400, 40)]
    it = iter(seq + [seq[-1]] * 50)
    mon = ContentionMonitor(interval_s=0.01, threshold=0.01, metrics=m,
                            reader=lambda: next(it))
    mon.start()
    import time as _t
    _t.sleep(0.15)
    s = mon.summary()
    assert s.get("competing_cpu_frac_mean", 0) > 0.0
    assert "contended" in s
    snap = m.snapshot()["gauges"]
    assert snap["host.competing_cpu_frac_mean"] == \
        s["competing_cpu_frac_mean"]
    assert snap["host.contended"] == float(s["contended"])


def test_monitor_degrades_without_procfs(tmp_path):
    mon = ContentionMonitor(stat_path=str(tmp_path / "missing"),
                            self_stat_path=str(tmp_path / "missing2"))
    assert mon._jiffies() is None
    mon.start()  # must not spawn a crashing thread
    s = mon.summary()
    assert "competing_cpu_frac_mean" not in s


def test_monitor_reexports():
    from explicit_hybrid_mpc_tpu.parallel.mesh import \
        ContentionMonitor as MeshCM
    assert MeshCM is ContentionMonitor
    sys.path.insert(0, REPO)
    try:
        import bench
        assert bench.ContentionMonitor is ContentionMonitor
    finally:
        sys.path.remove(REPO)


# -- profile_capture.summarize_trace (satellite) ---------------------------

def _write_trace(dirpath, events):
    run = os.path.join(dirpath, "plugins", "profile", "run1")
    os.makedirs(run)
    path = os.path.join(run, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_summarize_trace_top_ops(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from profile_capture import summarize_trace
    finally:
        sys.path.pop(0)
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fusion.1", "dur": 1500.0, "ts": 0},
        {"ph": "X", "name": "fusion.1", "dur": 500.0, "ts": 10},
        {"ph": "X", "name": "cholesky", "dur": 3000.0, "ts": 20},
        {"ph": "B", "name": "not_complete", "ts": 30},  # ignored
    ]
    _write_trace(str(tmp_path), events)
    out = summarize_trace(str(tmp_path), top_n=5)
    assert out["trace_files"] == 1
    assert out["events"] == 3
    assert out["tracks"] == ["/device:TPU:0"]
    top = {r["name"]: r["total_ms"] for r in out["top_ops_ms"]}
    assert top["cholesky"] == 3.0
    assert top["fusion.1"] == 2.0  # summed across events
    # Sorted by total duration, descending.
    assert out["top_ops_ms"][0]["name"] == "cholesky"


def test_summarize_trace_missing_dir(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from profile_capture import summarize_trace
    finally:
        sys.path.pop(0)
    out = summarize_trace(str(tmp_path / "nope"))
    assert "error" in out
