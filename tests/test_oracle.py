"""Oracle plugin-boundary tests: pointwise enumeration, feasibility
queries, simplex-min bounds, backend equivalence."""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def di():
    return make("double_integrator", N=3, theta_box=1.5)


@pytest.fixture(scope="module")
def oracle(di):
    return Oracle(di, backend="cpu")


def test_vertex_solutions_consistent(oracle, di, rng):
    thetas = rng.uniform(di.theta_lb, di.theta_ub, size=(16, 2))
    sol = oracle.solve_vertices(thetas)
    assert np.all(sol.dstar == 0)  # single commutation
    assert np.all(np.isfinite(sol.Vstar))
    # V* must equal the per-delta V at dstar.
    np.testing.assert_allclose(sol.Vstar, sol.V[:, 0])
    # Value function gradient check by finite differences.
    h = 1e-5
    for k in range(4):
        th = thetas[k]
        for ax in range(2):
            e = np.zeros(2)
            e[ax] = h
            Vp = oracle.solve_vertices((th + e)[None]).Vstar[0]
            Vm = oracle.solve_vertices((th - e)[None]).Vstar[0]
            fd = (Vp - Vm) / (2 * h)
            assert abs(fd - sol.grad[k, 0, ax]) < 1e-4 * (1 + abs(fd))


def test_point_feasibility_signs(oracle):
    t = oracle.feasibility(np.array([[0.0, 0.0], [80.0, 80.0]]),
                           np.array([0, 0]))
    assert t[0] <= 1e-8
    assert t[1] > 1.0


def test_simplex_feasibility_farkas(oracle):
    V_in = np.array([[0.0, 0.0], [0.5, 0.0], [0.0, 0.5]])
    V_out = V_in + 60.0  # far outside the reachable/constraint set
    Ms = np.stack([geometry.barycentric_matrix(V) for V in (V_in, V_out)])
    t, feas_somewhere, infeas_cert = oracle.simplex_feasibility(
        Ms, np.array([0, 0]))
    assert feas_somewhere[0] and not infeas_cert[0]
    assert infeas_cert[1] and not feas_somewhere[1]


def test_simplex_min_matches_vertex_min(oracle, di):
    """Exact simplex min must lower-bound (and for a tiny simplex approach)
    the vertex values."""
    V = np.array([[0.1, 0.1], [0.2, 0.1], [0.1, 0.2]])
    M = geometry.barycentric_matrix(V)[None]
    Vmin, feas = oracle.solve_simplex_min(M, np.array([0]))
    vert = oracle.solve_vertices(V)
    assert feas[0]
    assert Vmin[0] <= np.min(vert.Vstar) + 1e-6
    assert Vmin[0] > 0.0  # cost is PD quadratic-ish, away from origin


def test_simplex_chunking_matches_single_call(oracle, rng):
    """Chunked simplex queries (cap < K) must return exactly what one
    call returns -- the cap exists to bound compiled shapes, not to
    change results."""
    Vs = []
    for k in range(40):
        lo = rng.uniform(-0.5, 0.3, size=2)
        Vs.append(np.vstack([lo, lo + [0.2, 0.0], lo + [0.0, 0.2]]))
    Ms = np.stack([geometry.barycentric_matrix(V) for V in Vs])
    ds = np.zeros(40, dtype=np.int64)
    ref_min, ref_feas = oracle.solve_simplex_min(Ms, ds)
    ref_t, ref_sw, ref_ic = oracle.simplex_feasibility(Ms, ds)
    chunked = Oracle(oracle.problem, backend="cpu")
    chunked.max_simplex_rows_per_call = 16  # forces 3 chunks
    c_min, c_feas = chunked.solve_simplex_min(Ms, ds)
    c_t, c_sw, c_ic = chunked.simplex_feasibility(Ms, ds)
    np.testing.assert_array_equal(ref_min, c_min)
    np.testing.assert_array_equal(ref_feas, c_feas)
    np.testing.assert_array_equal(ref_t, c_t)
    np.testing.assert_array_equal(ref_sw, c_sw)
    np.testing.assert_array_equal(ref_ic, c_ic)


class _Unconstrained(base.HybridMPC):
    """Zero-constraint problem: stack_slices must pad to nc=1 and the IPM
    must solve it exactly (review finding: zero-row crash)."""

    name = "_unconstrained"

    def __init__(self):
        self.theta_lb = -np.ones(2)
        self.theta_ub = np.ones(2)
        self.n_u = 1

    def build_canonical(self):
        A = np.array([[1.0, 0.1], [0.0, 1.0]])
        B = np.array([[0.0], [0.1]])
        sl = base.condense(
            A_seq=[A] * 3, B_seq=[B] * 3, e_seq=[np.zeros(2)] * 3,
            Q=np.eye(2), R=np.eye(1), P=np.eye(2), E=np.eye(2),
            x_nom=np.zeros(2), n_u=1)
        return base.stack_slices([sl], deltas=np.zeros((1, 0), np.int64))


def test_zero_constraint_problem_solvable(rng):
    prob = _Unconstrained()
    can = prob.canonical
    assert can.nc == 1  # vacuous padding row
    o = Oracle(prob, backend="cpu")
    sol = o.solve_vertices(rng.uniform(-1, 1, size=(4, 2)))
    assert np.all(sol.conv)
    # Unconstrained optimum: z* = -H^{-1} (f + F theta).
    th = np.array([0.3, -0.2])
    sol1 = o.solve_vertices(th[None])
    z_exact = -np.linalg.solve(can.H[0], can.f[0] + can.F[0] @ th)
    np.testing.assert_allclose(sol1.z[0, 0], z_exact, atol=1e-7)


def test_truncated_run_reported():
    from explicit_hybrid_mpc_tpu.partition.frontier import build_partition

    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.01, backend="cpu", batch_simplices=8,
                          max_steps=3)
    res = build_partition(prob, cfg)
    assert res.stats["truncated"]
    assert res.stats["frontier_left"] > 0


def test_solve_pairs_matches_dense_grid():
    """The sparse (point, delta) pair path (masked vertex solves) must
    return exactly the dense solve_vertices grid's cells -- same program
    family, same precision, so bitwise equality is required for the
    masked build's tree parity."""
    prob = make("inverted_pendulum", N=2)
    oracle = Oracle(prob, backend="cpu")
    rng = np.random.default_rng(3)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(6, 2))
    dense = oracle.solve_vertices(thetas)
    nd = prob.canonical.n_delta
    # Every (point, delta) cell, in scrambled order + chunked.
    pt = np.repeat(np.arange(6), nd)
    ds = np.tile(np.arange(nd, dtype=np.int64), 6)
    perm = rng.permutation(pt.size)
    pairs = Oracle(prob, backend="cpu")
    pairs.max_pairs_per_call = 64  # force chunking
    V, conv, grad, u0, z = pairs.solve_pairs(thetas[pt[perm]], ds[perm])
    # conv and the V=+inf encoding must agree everywhere; grad/u0/z are
    # compared only where converged (unconverged cells hold divergence
    # garbage that differs between the two compiled programs and is never
    # read downstream -- certify masks every use by conv).
    np.testing.assert_array_equal(conv, dense.conv[pt[perm], ds[perm]])
    np.testing.assert_array_equal(V, dense.V[pt[perm], ds[perm]])
    c = conv
    np.testing.assert_array_equal(grad[c], dense.grad[pt[perm], ds[perm]][c])
    np.testing.assert_array_equal(u0[c], dense.u0[pt[perm], ds[perm]][c])
    np.testing.assert_array_equal(z[c], dense.z[pt[perm], ds[perm]][c])


def test_selective_phase1_skips_feasible_pairs(oracle, rng):
    """solve_simplex_min runs the phase-1 program only on pairs whose
    elastic min did not already witness feasibility; on an all-feasible
    batch the simplex-solve count is ~1 per pair, not 2."""
    Vs = []
    for k in range(8):
        lo = rng.uniform(-0.5, 0.3, size=2)
        Vs.append(np.vstack([lo, lo + [0.2, 0.0], lo + [0.0, 0.2]]))
    Ms = np.stack([geometry.barycentric_matrix(V) for V in Vs])
    ds = np.zeros(8, dtype=np.int64)
    before = oracle.n_simplex_solves
    Vmin, feas = oracle.solve_simplex_min(Ms, ds)
    issued = oracle.n_simplex_solves - before
    assert np.all(feas)            # di is feasible everywhere in the box
    assert np.all(np.isfinite(Vmin))
    assert issued < 2 * 8          # the old cost was exactly 2 per pair


def test_rescue_recovers_short_point_schedule():
    """An aggressive point schedule plus rescue must recover the full
    schedule's converged set: rescue re-solves exactly the
    feasible-but-unconverged stragglers cold at full f64 length."""
    prob = make("inverted_pendulum", N=3)
    rng = np.random.default_rng(5)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(24, 2))
    base = Oracle(prob, backend="cpu", n_iter=30)
    short = Oracle(prob, backend="cpu", n_iter=30, precision="mixed",
                   n_f32=20, point_schedule=(8, 4))
    resc = Oracle(prob, backend="cpu", n_iter=30, precision="mixed",
                  n_f32=20, point_schedule=(8, 4), rescue_iter=30)
    sb, ss, sr = (o.solve_vertices(thetas) for o in (base, short, resc))
    # The short schedule must actually lose some cells for this test to
    # exercise anything; the rescue pass then restores them.
    assert ss.conv.sum() < sb.conv.sum()
    assert resc.n_rescue_solves > 0
    assert sr.conv.sum() >= sb.conv.sum()
    # Rescued values agree with the full-schedule solve (mask BEFORE the
    # subtraction: unconverged cells hold +inf and inf - inf warns).
    both = sb.conv & sr.conv
    assert np.allclose(sr.V[both], sb.V[both], atol=1e-6)
    np.testing.assert_array_equal(sr.dstar, sb.dstar)


def test_stage2_orders_agree_on_hybrid():
    """phase1-first (the hybrid auto default) and min-first must return
    the same (Vmin, feasible_somewhere) encodings on a mixed batch of
    feasible and infeasible (simplex, delta) pairs, with phase1-first
    issuing fewer joint QPs when infeasible pairs dominate."""
    prob = make("inverted_pendulum", N=2)
    rng = np.random.default_rng(9)
    Ms, ds = [], []
    nd = prob.canonical.n_delta
    for k in range(24):
        lo = rng.uniform(prob.theta_lb, prob.theta_ub * 0.6)
        V = np.vstack([lo, lo + [0.15, 0.0], lo + [0.0, 0.15]])
        Ms.append(geometry.barycentric_matrix(V))
        ds.append(k % nd)
    Ms = np.stack(Ms)
    ds = np.asarray(ds, dtype=np.int64)
    o_p1 = Oracle(prob, backend="cpu")          # auto -> phase1_first
    assert o_p1.stage2_phase1_first
    o_min = Oracle(prob, backend="cpu", stage2_order="min_first")
    V1, f1 = o_p1.solve_simplex_min(Ms, ds)
    V2, f2 = o_min.solve_simplex_min(Ms, ds)
    np.testing.assert_array_equal(V1, V2)
    np.testing.assert_array_equal(f1, f2)
    # The batch must actually exercise both outcomes for the equality to
    # mean anything.
    assert np.any(V1 == np.inf) and np.any(np.isfinite(V1))
    assert o_p1.n_simplex_solves < o_min.n_simplex_solves
