"""Closed-loop simulator: explicit-vs-implicit parity, regulation,
hybrid plant switching, and noise handling."""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import export
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make
from explicit_hybrid_mpc_tpu.sim import simulator


@pytest.fixture(scope="module")
def di_setup():
    prob = make("double_integrator", N=3, theta_box=1.5)
    oracle = Oracle(prob, backend="cpu")
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.05,
                          backend="cpu", batch_simplices=64)
    res = build_partition(prob, cfg, oracle=oracle)
    return prob, oracle, export.export_leaves(res.tree)


def test_explicit_regulates_to_origin(di_setup):
    prob, oracle, table = di_setup
    res = simulator.simulate(
        prob, simulator.ExplicitController(table),
        np.array([1.0, -0.5]), T=40)
    assert np.all(res.inside)
    assert np.linalg.norm(res.states[-1]) < 1e-2
    assert np.all(np.abs(res.inputs) <= prob.u_max + 1e-8)


def test_explicit_tracks_implicit(di_setup):
    """Closed-loop trajectories must agree within the certificate's
    resolution (eps_a=0.05 -> near-identical inputs away from ties)."""
    prob, oracle, table = di_setup
    cmp = simulator.compare(prob, table, oracle,
                            np.array([-1.2, 0.8]), T=30)
    assert np.all(cmp.explicit.inside)
    # Certified eps-suboptimality shows up as closed-loop cost parity.
    assert cmp.cost_ratio < 1.05
    err = np.max(np.abs(cmp.explicit.states - cmp.implicit.states))
    assert err < 0.2  # same qualitative trajectory


def test_pendulum_hybrid_switching(di_setup):
    """Pendulum from inside the wall region: the plant must visit both
    modes and the explicit law must still regulate."""
    prob = make("inverted_pendulum", N=3)
    oracle = Oracle(prob, backend="cpu")
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_steps=400)
    res = build_partition(prob, cfg, oracle=oracle)
    table = export.export_leaves(res.tree)
    sim = simulator.simulate(
        prob, simulator.ExplicitController(table),
        np.array([0.3, 0.5]), T=60)
    th = sim.states[:, 0]
    assert np.any(th > 0) and np.any(th < 0)   # both modes visited
    assert np.linalg.norm(sim.states[-1]) < 0.05


def test_pallas_backend_matches_jax(di_setup):
    """ExplicitController(backend='pallas') must produce the same closed
    loop as the pure-JAX backend; interpret auto-detects off-TPU (ADVICE
    round 1: the pallas sim path was TPU-only and untested)."""
    prob, oracle, table = di_setup
    theta0 = np.array([0.9, -0.4])
    ref = simulator.simulate(
        prob, simulator.ExplicitController(table, backend="jax"),
        theta0, T=15)
    pal = simulator.simulate(
        prob, simulator.ExplicitController(table, backend="pallas"),
        theta0, T=15)
    np.testing.assert_allclose(pal.inputs, ref.inputs, atol=1e-6)
    np.testing.assert_allclose(pal.states, ref.states, atol=1e-5)


def test_noise_and_cost_accounting(di_setup, rng):
    prob, oracle, table = di_setup
    noise = 0.01 * rng.normal(size=(20, 2))
    res = simulator.simulate(
        prob, simulator.ExplicitController(table),
        np.array([0.5, 0.5]), T=20, noise=noise)
    assert res.states.shape == (21, 2)
    assert res.stage_costs.shape == (20,)
    assert res.total_cost > 0
    # Stage costs recompute from the recorded trajectory.
    c0 = prob.stage_cost(res.states[0], res.inputs[0])
    assert np.isclose(c0, res.stage_costs[0])


def test_semi_explicit_online_stage():
    """The feasibility-only variant's intended deployment: locate fixes
    the leaf's delta, a small fixed-delta QP runs online.  The emitted
    input must come from a CONVERGED, constraint-satisfying QP at every
    certified-leaf parameter (round-1 verdict: the interpolating evaluator
    carries no guarantee for feasibility-only leaves)."""
    prob = make("inverted_pendulum", N=3)
    oracle = Oracle(prob, backend="cpu")
    cfg = PartitionConfig(problem="inverted_pendulum",
                          algorithm="feasible", backend="cpu",
                          batch_simplices=64, max_steps=400)
    res = build_partition(prob, cfg, oracle=oracle)
    table = export.export_leaves(res.tree)
    can = prob.canonical
    tree = res.tree
    rng = np.random.default_rng(7)
    thetas, ds = [], []
    leaves = tree.converged_leaves()
    for n in leaves[::max(1, len(leaves) // 25)]:
        lam = rng.dirichlet(np.ones(tree.vertices[n].shape[0]))
        thetas.append(lam @ tree.vertices[n])
        ds.append(tree.leaf_data[n].delta_idx)
    u0, V, conv, z = oracle.solve_fixed(np.stack(thetas), np.array(ds))
    # The offline certificate (delta feasible at every vertex => on the
    # whole leaf, by convexity) makes the online QP feasible everywhere.
    assert np.all(conv)
    for k, (th, d) in enumerate(zip(thetas, ds)):
        viol = np.max(can.G[d] @ z[k] - can.w[d] - can.S[d] @ th)
        assert viol <= 1e-6, f"leaf sample {k}: violation {viol}"
    # Closed loop under the semi-explicit controller regulates and
    # respects input bounds.
    sim = simulator.simulate(
        prob, simulator.SemiExplicitController(table, oracle),
        np.array([0.3, 0.5]), T=50)
    assert np.linalg.norm(sim.states[-1]) < 0.05
    assert np.all(np.abs(sim.inputs) <= prob.u_max + 1e-6)
    assert np.all(sim.inside)


def test_satellite_closed_loop_desaturates():
    """From saturated wheel momentum the closed loop must pull |h| down
    (thruster firing), ending far below the start."""
    prob = make("satellite", axes=1, N=3)
    oracle = Oracle(prob, backend="cpu")
    imp = simulator.simulate(
        prob, simulator.ImplicitController(oracle),
        np.array([0.0, 1.0]), T=25)
    assert abs(imp.states[-1, 1]) < 0.3 * 1.0
    assert np.all(np.isfinite(imp.inputs))