"""B&B-style serial baseline (oracle/bnb.py): bound validity, parity with
flat enumeration, and that pruning actually prunes.

The baseline exists so bench.py's vs_baseline_bnb prices the reference's
serial branch-and-bound oracle honestly (SURVEY.md section 4.1 hot loop;
round-3 verdict item 8).  Its correctness contract: same Vstar as the
enumeration oracle (what the partition engine consumes), never more QPs
than flat enumeration.
"""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.oracle.bnb import SerialBnB
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def pendulum():
    return make("inverted_pendulum", N=3)


@pytest.fixture(scope="module")
def serial(pendulum):
    return Oracle(pendulum, backend="serial")


@pytest.fixture(scope="module")
def points(pendulum):
    rng = np.random.default_rng(77)
    return rng.uniform(pendulum.theta_lb, pendulum.theta_ub,
                       size=(12, pendulum.n_theta))


def test_requires_serial_backend(pendulum):
    with pytest.raises(ValueError, match="serial"):
        SerialBnB(Oracle(pendulum, backend="cpu"))


def test_root_bounds_are_lower_bounds(serial, points):
    bnb = SerialBnB(serial)
    sol = serial.solve_vertices(points)
    for i, th in enumerate(points):
        lbs = bnb.root_bounds(th)
        conv = sol.conv[i]
        slack = 1e-6 * np.maximum(1.0, np.abs(sol.V[i][conv]))
        assert np.all(lbs[conv] <= sol.V[i][conv] + slack), (
            f"point {i}: root bound above the converged QP optimum")


def test_bnb_matches_enumeration(serial, points, pendulum):
    bnb = SerialBnB(serial)
    sol = serial.solve_vertices(points)
    nd = pendulum.canonical.n_delta
    for i, th in enumerate(points):
        V, d, n_qp = bnb.solve_point(th)
        assert n_qp <= nd
        if np.isfinite(sol.Vstar[i]):
            assert np.isfinite(V)
            assert np.isclose(V, sol.Vstar[i], rtol=1e-6, atol=1e-8), (
                f"point {i}: bnb {V} vs enumeration {sol.Vstar[i]}")
            # dstar may legitimately differ on exact cost ties; the chosen
            # commutation's own cost must equal the optimum.
            assert np.isclose(sol.V[i][d], sol.Vstar[i],
                              rtol=1e-6, atol=1e-8)
        else:
            assert not np.isfinite(V) and d == -1


def test_pruning_happens(serial, points, pendulum):
    """On the pendulum family the unconstrained bounds separate modes
    well enough that best-first beats flat enumeration on average."""
    bnb = SerialBnB(serial)
    stats = bnb.measure(points)
    assert stats["qp_per_point"] <= pendulum.canonical.n_delta
    assert stats["pruned_per_point"] > 0, (
        "no commutation was ever pruned -- bound or ordering is broken")
