"""SLO engine tests (obs/slo.py): spec validation, the three fold
kinds, multi-window burn semantics (the ISSUE-20 acceptance pair:
a sustained breach fires the fast pair, an equal-magnitude brief
spike does not), and bitwise restart survival of the error budget
through the checksummed state file."""

import struct

import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl
from explicit_hybrid_mpc_tpu.obs.slo import (SloSpec, SloTracker,
                                             build_slo_specs,
                                             lifecycle_slo_specs,
                                             serve_slo_specs)

#: Test-scaled window geometry: fast pair 5s/60s, slow pair 120s/600s
#: over a 1 s ring interval (600 slots).  Same shape as serve_bench's
#: sub-second config -- the production 5m/1h + 6h/3d defaults only
#: change the constants.
WINDOWS = ((5.0, 60.0), (120.0, 600.0))
THRESH = (14.4, 1.0)


def _tracker(**kw):
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("windows", WINDOWS)
    kw.setdefault("burn_thresholds", THRESH)
    return SloTracker(**kw)


def _avail_spec(goal=0.999):
    return SloSpec(name="t.avail", kind="counter", metric="bad",
                   total=("total",), goal=goal)


def _feed(tr, t, bad_cum, tot_cum):
    """One tick with cumulative counter values (the fold is
    snapshot-delta, like a real metrics registry)."""
    return tr.tick({"counters": {"bad": float(bad_cum),
                                 "total": float(tot_cum)}}, now=t)


# -- spec validation -------------------------------------------------------

def test_spec_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown slo kind"):
        SloSpec(name="x", kind="ratio", metric="m")


def test_spec_rejects_goal_out_of_range():
    for goal in (0.0, 1.0, -0.5, 2.0):
        with pytest.raises(ValueError, match="goal"):
            SloSpec(name="x", kind="counter", metric="m",
                    total=("t",), goal=goal)


def test_spec_rejects_thresholdless_hist_and_gauge():
    for kind in ("hist_p", "gauge"):
        with pytest.raises(ValueError, match="threshold"):
            SloSpec(name="x", kind=kind, metric="m")


def test_counter_spec_normalizes_string_total():
    sp = SloSpec(name="x", kind="counter", metric="m", total="tot")
    assert sp.total == ("tot",)
    with pytest.raises(ValueError, match="total"):
        SloSpec(name="x", kind="counter", metric="m")


def test_tracker_rejects_bad_geometry():
    with pytest.raises(ValueError, match="finer than"):
        SloTracker(interval_s=10.0, windows=((5.0, 60.0),),
                   burn_thresholds=(1.0,))
    with pytest.raises(ValueError, match="1:1"):
        SloTracker(interval_s=1.0, windows=WINDOWS,
                   burn_thresholds=(1.0,))


# -- fold kinds ------------------------------------------------------------

def test_counter_fold_and_compliance():
    tr = _tracker(specs=[_avail_spec()])
    _feed(tr, 0.0, 0, 0)               # baseline
    for i in range(1, 11):
        _feed(tr, float(i), 2 * i, 100 * i)   # 2% bad per interval
    rep = tr.evaluate()["t.avail"]
    assert rep["good"] == 980.0 and rep["bad"] == 20.0
    assert rep["compliance"] == pytest.approx(0.98)
    # goal 0.999 allows 1 bad unit per 1000: 20 bad of 1000 = 20x the
    # whole budget -> deeply negative remaining (uncapped by design).
    assert rep["budget_remaining_frac"] < -10


def test_counter_fold_tolerates_registry_restart():
    tr = _tracker(specs=[_avail_spec()])
    _feed(tr, 0.0, 5, 100)
    _feed(tr, 1.0, 2, 40)   # cumulative went BACKWARDS: fresh registry
    rep = tr.evaluate()["t.avail"]
    # Second tick folds the new cumulative as-is, never a negative delta.
    assert rep["bad"] == 7.0 and rep["good"] == 133.0


def test_hist_fold_splits_at_threshold():
    sp = SloSpec(name="t.p99", kind="hist_p", metric="lat",
                 threshold=100.0)
    tr = _tracker(specs=[sp])
    h1 = {"bounds": [10.0, 100.0, 1000.0], "counts": [5, 3, 2, 1],
          "count": 11}
    tr.tick({"histograms": {"lat": h1}}, now=0.0)
    rep = tr.evaluate()["t.p99"]
    # bisect_right(bounds, 100) == 2: buckets <= threshold are good.
    assert rep["good"] == 8.0 and rep["bad"] == 3.0
    # Delta fold: only the new observations count on the next tick.
    h2 = {"bounds": [10.0, 100.0, 1000.0], "counts": [6, 3, 2, 5],
          "count": 16}
    tr.tick({"histograms": {"lat": h2}}, now=1.0)
    rep = tr.evaluate()["t.p99"]
    assert rep["good"] == 9.0 and rep["bad"] == 7.0


def test_gauge_fold_one_unit_per_tick_absent_is_silent():
    sp = SloSpec(name="t.stale", kind="gauge", metric="staleness_s",
                 threshold=10.0)
    tr = _tracker(specs=[sp])
    tr.tick({"gauges": {}}, now=0.0)          # absent: no unit
    rep = tr.evaluate()["t.stale"]
    assert rep["good"] == 0.0 and rep["bad"] == 0.0
    tr.tick({"gauges": {"staleness_s": 3.0}}, now=1.0)
    tr.tick({"gauges": {"staleness_s": 30.0}}, now=2.0)
    rep = tr.evaluate()["t.stale"]
    assert rep["good"] == 1.0 and rep["bad"] == 1.0


def test_gap_zero_fills_and_burn_clears():
    tr = _tracker(specs=[_avail_spec()])
    _feed(tr, 0.0, 0, 0)
    _feed(tr, 1.0, 50, 100)    # 50% bad: burning hard
    assert tr.evaluate()["t.avail"]["burn_fast"] > THRESH[0]
    # 70 s of silence: the gap zero-fills, both fast windows roll off.
    _feed(tr, 71.0, 50, 100)   # unchanged cumulatives = no new units
    rep = tr.evaluate()["t.avail"]
    assert rep["burn_fast"] == 0.0
    # The budget window (600 s) still remembers the spend.
    assert rep["bad"] == 50.0


# -- burn semantics (the acceptance pair) ----------------------------------

def _burn_events(path, window):
    return [r for r in load_jsonl(path)
            if r.get("kind") == "event"
            and r.get("name") == "health.slo_burn"
            and r.get("window") == window]


def test_sustained_breach_fires_fast_pair_once(tmp_path):
    p = str(tmp_path / "s.obs.jsonl")
    with obs_lib.Obs("jsonl", path=p) as o:
        tr = _tracker(specs=[_avail_spec()], obs=o)
        _feed(tr, 0.0, 0, 0)
        # 30% bad sustained for 130 intervals: burn 300x on every
        # window, far past the 14.4x fast threshold.
        for i in range(1, 131):
            _feed(tr, float(i), 30 * i, 100 * i)
    fast = _burn_events(p, "fast")
    # Rising edge only: a sustained breach pages ONCE, not per tick.
    assert len(fast) == 1
    ev = fast[0]
    assert ev["severity"] == "critical" and ev["spec"] == "t.avail"
    assert ev["value"] > 14.4
    assert "docs/observability.md" in ev["msg"]  # runbook pointer


def test_brief_spike_of_same_magnitude_does_not_fire_fast(tmp_path):
    p = str(tmp_path / "s.obs.jsonl")
    with obs_lib.Obs("jsonl", path=p) as o:
        tr = _tracker(specs=[_avail_spec()], obs=o)
        _feed(tr, 0.0, 0, 0)
        # 60 s of clean traffic fills the fast pair's long window...
        for i in range(1, 61):
            _feed(tr, float(i), 0, 100 * i)
        # ...then ONE interval at the same 30% bad magnitude...
        _feed(tr, 61.0, 30, 6100)
        # ...then clean again.
        for i in range(62, 70):
            _feed(tr, float(i), 30, 100 * i)
    # Short window burns (30/500 = 60x) but the 60 s window dilutes
    # the spike to ~5x < 14.4: the published burn is the MIN across
    # the pair, so the fast alert never fires.
    assert _burn_events(p, "fast") == []


def test_cleared_then_returned_breach_fires_again(tmp_path):
    p = str(tmp_path / "s.obs.jsonl")
    with obs_lib.Obs("jsonl", path=p) as o:
        tr = _tracker(specs=[_avail_spec()], obs=o)
        _feed(tr, 0.0, 0, 0)
        for i in range(1, 11):
            _feed(tr, float(i), 30 * i, 100 * i)      # breach #1
        bad, tot = 300, 1000
        # 70 s clean: every fast window rolls the breach off.
        for i in range(11, 81):
            tot += 100
            _feed(tr, float(i), bad, tot)
        for i in range(81, 91):                        # breach #2
            bad += 30
            tot += 100
            _feed(tr, float(i), bad, tot)
    assert len(_burn_events(p, "fast")) == 2


# -- durability ------------------------------------------------------------

def _bits(x: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", x))[0]


def test_budget_survives_restart_bitwise(tmp_path):
    sd = str(tmp_path / "slo")
    tr = _tracker(specs=[_avail_spec()], state_dir=sd, identity="t")
    _feed(tr, 0.0, 0, 0)
    # Awkward floats on purpose: the state file must round-trip the
    # exact doubles (json repr), not a decimal approximation.
    for i in range(1, 31):
        _feed(tr, float(i), 0.1 * i, 33.3 * i)
    before = tr.evaluate(now=30.0)["t.avail"]
    tr.flush()

    tr2 = _tracker(specs=[_avail_spec()], state_dir=sd, identity="t")
    after = tr2.evaluate(now=30.0)["t.avail"]
    for field in ("good", "bad", "compliance", "budget_remaining_frac",
                  "burn_fast", "burn_slow"):
        assert _bits(after[field]) == _bits(before[field]), field


def test_restart_preserves_runtime_discovered_specs(tmp_path):
    sd = str(tmp_path / "slo")
    tpl = {"p99_target_us": 1000.0, "goal": 0.99}
    tr = _tracker(serve_template=tpl, state_dir=sd, identity="t")
    tr.tick({"counters": {"serve.ctl.A.requests": 100,
                          "serve.ctl.A.fallbacks": 7}}, now=0.0)
    tr.tick({"counters": {"serve.ctl.A.requests": 200,
                          "serve.ctl.A.fallbacks": 7}}, now=1.0)
    tr.flush()
    # The restarted tracker gets NO spec list and NO template traffic
    # yet: the persisted spec definitions must restore the budget.
    tr2 = _tracker(serve_template=tpl, state_dir=sd, identity="t")
    rep = tr2.evaluate(now=1.0)
    assert rep["A.fallback"]["bad"] == 7.0
    assert {"A.p99", "A.p99_roll", "A.fallback"} <= set(rep)


def test_corrupt_state_rejected_starts_empty(tmp_path):
    sd = str(tmp_path / "slo")
    tr = _tracker(specs=[_avail_spec()], state_dir=sd, identity="t")
    _feed(tr, 0.0, 0, 0)
    _feed(tr, 1.0, 5, 100)
    tr.flush()
    with open(tr._state_path(), "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff\xff")   # bit rot past the checksum header
    tr2 = _tracker(specs=[_avail_spec()], state_dir=sd, identity="t")
    rep = tr2.evaluate(now=1.0)["t.avail"]
    assert rep["good"] == 0.0 and rep["bad"] == 0.0


def test_geometry_mismatch_rejected(tmp_path):
    sd = str(tmp_path / "slo")
    tr = _tracker(specs=[_avail_spec()], state_dir=sd, identity="t")
    _feed(tr, 0.0, 0, 0)
    _feed(tr, 1.0, 5, 100)
    tr.flush()
    tr2 = SloTracker([_avail_spec()], interval_s=2.0, windows=WINDOWS,
                     burn_thresholds=THRESH, state_dir=sd, identity="t")
    rep = tr2.evaluate(now=1.0)["t.avail"]
    assert rep["good"] == 0.0 and rep["bad"] == 0.0


# -- factories + publication ----------------------------------------------

def test_spec_factories_cover_documented_objectives():
    names = {s.name for s in serve_slo_specs(
        "A", p99_target_us=1000.0, subopt_eps=0.01)}
    assert names == {"A.p99", "A.p99_roll", "A.fallback", "A.subopt"}
    assert {s.name for s in lifecycle_slo_specs(sla_s=60.0)} \
        == {"lifecycle.staleness", "lifecycle.staleness_p99"}
    (b,) = build_slo_specs()
    assert b.metric == "build.quarantined_cells" and b.kind == "counter"


def test_published_unit_counters_are_lifetime_sums(tmp_path):
    p = str(tmp_path / "s.obs.jsonl")
    with obs_lib.Obs("jsonl", path=p) as o:
        tr = _tracker(specs=[_avail_spec()], obs=o)
        _feed(tr, 0.0, 0, 0)
        _feed(tr, 1.0, 3, 100)
        _feed(tr, 2.0, 5, 250)
        snap = o.metrics.snapshot()
    c = snap["counters"]
    # Counters carry lifetime unit totals (fleet rollup SUMS them
    # across shards); gauges carry the current verdict.
    assert c["slo.t.avail.bad_units"] == 5.0
    assert c["slo.t.avail.good_units"] == 245.0
    g = snap["gauges"]
    assert g["slo.t.avail.goal"] == 0.999
    assert 0.0 < g["slo.t.avail.compliance"] < 1.0
    for k in ("burn_fast", "burn_slow", "budget_remaining_frac"):
        assert f"slo.t.avail.{k}" in g
