"""Fault-injection framework + crash-safe supervision (ISSUE 12).

Covers: FaultPlan/FaultSpec parsing + deterministic firing; the
atomic-write/checksummed-pickle utility; checkpoint generations
(corrupt-primary -> .prev fallback -> resumed build bit-matches the
straight-through build); truncated-artifact rejection + the registry
keeping its previous version; retry/backoff/quarantine around oracle
solves; the device-failure degrade cap; solve-timeout recovery;
registry lease-leak detection and publish atomicity under injection;
the max_quarantine_frac health rule; and the faults obs surface.
"""

import json
import os
import threading

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import faults
from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.faults import (FaultPlan, FaultSpec,
                                            InjectedCrash, InjectedFault)
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        build_partition,
                                                        load_checkpoint,
                                                        make_oracle)
from explicit_hybrid_mpc_tpu.problems.registry import make
from explicit_hybrid_mpc_tpu.utils import atomic


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """Every test leaves the process with no installed injector (a
    leaked plan would fire into unrelated tests' builds)."""
    yield
    faults.clear()


@pytest.fixture(scope="module")
def di_problem():
    return make("double_integrator", N=3, theta_box=1.5)


@pytest.fixture(scope="module")
def pend_problem():
    return make("inverted_pendulum", N=2)


def _cfg(**kw):
    base = dict(eps_a=0.5, backend="cpu", batch_simplices=32,
                oracle_retry_backoff_s=0.0)
    base.update(kw)
    return PartitionConfig(**base)


def _pend_cfg(**kw):
    return _cfg(problem="inverted_pendulum", max_depth=10, **kw)


@pytest.fixture(scope="module")
def di_clean(di_problem):
    return build_partition(di_problem, _cfg())


@pytest.fixture(scope="module")
def pend_clean(pend_problem):
    return build_partition(pend_problem, _pend_cfg())


# -- plan / injector -------------------------------------------------------

def test_plan_roundtrip_and_validation(tmp_path):
    plan = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "at": 3, "count": 2,
         "match": "simplex"},
        {"site": "checkpoint.write", "kind": "crash"},), seed=9,
        process_exit=True)
    p = tmp_path / "plan.json"
    plan.save(str(p))
    back = FaultPlan.from_json(str(p))
    assert back == plan
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec(site="nope", kind="error")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(site="oracle.call", kind="explode")
    with pytest.raises(ValueError, match="at"):
        FaultSpec(site="oracle.call", kind="error", at=0)
    with pytest.raises(ValueError, match="unknown FaultPlan keys"):
        FaultPlan.from_dict({"surprise": 1})


def test_injector_deterministic_firing():
    plan = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "at": 2, "count": 2},))
    with faults.activate(plan) as inj:
        faults.fire("oracle.call")              # 1: no-op
        with pytest.raises(InjectedFault):
            faults.fire("oracle.call")          # 2: fires
        with pytest.raises(InjectedFault):
            faults.fire("oracle.call")          # 3: fires (count=2)
        faults.fire("oracle.call")              # 4: done
        faults.fire("oracle.wait")              # other site untouched
    assert inj.n_fired() == 2
    assert inj.count("oracle.call") == 4
    # label matching narrows the counter's applicability, not the count
    plan2 = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "match": "simplex"},))
    with faults.activate(plan2) as inj2:
        faults.fire("oracle.call", label="solve_points")  # no match
        with pytest.raises(AssertionError):
            inj2.assert_all_fired()


def test_injector_crash_kinds():
    with faults.activate(FaultPlan(faults=(
            {"site": "build.step", "kind": "crash"},))):
        with pytest.raises(InjectedCrash):
            faults.fire("build.step")
    # InjectedCrash must NOT be swallowed by device-failure handlers
    assert not issubclass(InjectedCrash, (RuntimeError, OSError))


def test_fire_is_noop_without_plan():
    faults.clear()
    faults.fire("oracle.call")  # must not raise
    assert faults.current() is None


# -- atomic utility --------------------------------------------------------

def test_atomic_write_and_checksummed_pickle(tmp_path):
    p = tmp_path / "obj.pkl"
    atomic.atomic_pickle(str(p), {"a": 1})
    obj, checked = atomic.read_checked_pickle(str(p))
    assert obj == {"a": 1} and checked
    # legacy (no trailer) loads with checked=False
    import pickle

    legacy = tmp_path / "legacy.pkl"
    legacy.write_bytes(pickle.dumps([1, 2]))
    obj, checked = atomic.read_checked_pickle(str(legacy))
    assert obj == [1, 2] and not checked
    # truncation -> CorruptArtifact with a clear message
    data = p.read_bytes()
    p.write_bytes(data[:len(data) // 2])
    with pytest.raises(atomic.CorruptArtifact):
        atomic.read_checked_pickle(str(p))
    # bit flip under the checksum -> caught
    bad = bytearray(data)
    bad[5] ^= 0x40
    p.write_bytes(bytes(bad))
    with pytest.raises(atomic.CorruptArtifact, match="checksum"):
        atomic.read_checked_pickle(str(p))


def test_append_line_fsync(tmp_path):
    p = tmp_path / "h.jsonl"
    atomic.append_line_fsync(str(p), json.dumps({"x": 1}))
    atomic.append_line_fsync(str(p), json.dumps({"x": 2}) + "\n")
    rows = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert rows == [{"x": 1}, {"x": 2}]


def test_atomic_write_leaves_no_tmp_on_error(tmp_path, monkeypatch):
    p = tmp_path / "x.bin"

    def boom(src, dst):
        raise OSError("disk on fire")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        atomic.atomic_write_bytes(str(p), b"data")
    assert list(tmp_path.iterdir()) == []  # tmp file cleaned up


# -- checkpoint generations + crash recovery -------------------------------

def test_checkpoint_generation_fallback_and_resume_parity(
        tmp_path, pend_problem, pend_clean):
    cfg = _pend_cfg()
    ck = str(tmp_path / "b.ckpt.pkl")
    eng = FrontierEngine(pend_problem, make_oracle(pend_problem, cfg),
                         cfg)
    for _ in range(3):
        eng.step()
    eng.save_checkpoint(ck)
    for _ in range(2):
        eng.step()
    eng.save_checkpoint(ck)          # rotates gen 1 -> .prev
    assert os.path.exists(ck + ".prev")
    # SIGKILL-mid-write stand-in: the primary is torn at an arbitrary
    # byte; the loader must REJECT it and fall back to .prev.
    with open(ck, "r+b") as f:
        f.truncate(os.path.getsize(ck) // 2)
    with pytest.warns(RuntimeWarning, match="previous generation"):
        snap = load_checkpoint(ck)
    assert snap["steps"] == 3
    eng2 = FrontierEngine.resume(snap, pend_problem,
                                 make_oracle(pend_problem, cfg), cfg=cfg)
    while eng2.frontier:
        eng2.step()
    # The resumed-from-fallback build bit-matches the straight build.
    assert np.array_equal(pend_clean.tree.vertices, eng2.tree.vertices)
    assert eng2.n_uncertified == pend_clean.stats["uncertified"]


def test_checkpoint_both_generations_dead(tmp_path, pend_problem):
    cfg = _pend_cfg()
    ck = str(tmp_path / "b.ckpt.pkl")
    eng = FrontierEngine(pend_problem, make_oracle(pend_problem, cfg),
                         cfg)
    eng.step()
    eng.save_checkpoint(ck)
    eng.save_checkpoint(ck)
    for p in (ck, ck + ".prev"):
        with open(p, "r+b") as f:
            f.truncate(16)
    with pytest.raises(atomic.CorruptArtifact,
                       match="no valid checkpoint generation"):
        load_checkpoint(ck)


def test_injected_kill_mid_checkpoint_inprocess(tmp_path, pend_problem):
    """crash between rotation and write: the primary vanishes, .prev
    carries the previous generation, and the loader recovers."""
    cfg = _pend_cfg()
    ck = str(tmp_path / "b.ckpt.pkl")
    eng = FrontierEngine(pend_problem, make_oracle(pend_problem, cfg),
                         cfg)
    eng.step()
    eng.save_checkpoint(ck)
    eng.step()
    with faults.activate(FaultPlan(faults=(
            {"site": "checkpoint.write", "kind": "crash"},))):
        with pytest.raises(InjectedCrash):
            eng.save_checkpoint(ck)
    assert not os.path.exists(ck) and os.path.exists(ck + ".prev")
    with pytest.warns(RuntimeWarning, match="previous generation"):
        snap = load_checkpoint(ck)
    assert snap["steps"] == 1


def test_checkpoint_corrupt_injection_rejected(tmp_path, pend_problem):
    cfg = _pend_cfg()
    ck = str(tmp_path / "b.ckpt.pkl")
    eng = FrontierEngine(pend_problem, make_oracle(pend_problem, cfg),
                         cfg)
    eng.step()
    with faults.activate(FaultPlan(faults=(
            {"site": "checkpoint.written", "kind": "corrupt",
             "keep_frac": 0.6},))):
        eng.save_checkpoint(ck)
    with pytest.raises(atomic.CorruptArtifact):
        load_checkpoint(ck, fallback=False)


# -- truncated artifacts ---------------------------------------------------

def test_truncated_artifact_rejected_registry_keeps_old(
        tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.serve.registry import (ControllerRegistry,
                                                        save_artifacts)

    d1 = str(tmp_path / "v1")
    d2 = str(tmp_path / "v2")
    save_artifacts(di_clean.tree, di_clean.roots, d1)
    save_artifacts(di_clean.tree, di_clean.roots, d2)
    reg = ControllerRegistry()
    v1 = reg.load_artifacts("ctl", "v1", d1)
    # Torn second-generation artifact: truncate a field file.
    with open(os.path.join(d2, "bary_M.npy"), "r+b") as f:
        f.truncate(os.path.getsize(os.path.join(d2, "bary_M.npy")) // 3)
    with pytest.raises(atomic.CorruptArtifact):
        reg.load_artifacts("ctl", "v2", d2)
    # The registry still serves the previous valid generation.
    assert reg.active_version("ctl") == "v1"
    with reg.lease("ctl") as ver:
        assert ver is v1


def test_artifact_checksum_verify(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.online import export

    d = str(tmp_path / "t")
    export.write_leaf_table(di_clean.tree, d)
    export.load_leaf_table(d, verify_checksum=True)  # clean passes
    # Flip a payload byte INSIDE the array data: shape stays valid, so
    # only the checksum can catch it.
    p = os.path.join(d, "V.npy")
    with open(p, "r+b") as f:
        f.seek(os.path.getsize(p) - 3)
        b = f.read(1)
        f.seek(os.path.getsize(p) - 3)
        f.write(bytes([b[0] ^ 1]))
    with pytest.raises(atomic.CorruptArtifact, match="sha256"):
        export.load_leaf_table(d, verify_checksum=True)


def test_artifact_meta_commit_marker_mismatch(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.online import export

    d = str(tmp_path / "t")
    export.write_leaf_table(di_clean.tree, d)
    meta_p = os.path.join(d, "meta.json")
    with open(meta_p) as f:
        meta = json.load(f)
    meta["n_leaves"] += 5  # stale commit marker vs arrays
    meta.pop("checksums", None)
    with open(meta_p, "w") as f:
        json.dump(meta, f)
    with pytest.raises(atomic.CorruptArtifact, match="meta.json"):
        export.load_leaf_table(d)


def test_corrupt_injection_on_artifact_written(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.online import export

    d = str(tmp_path / "t")
    with faults.activate(FaultPlan(faults=(
            {"site": "artifact.written", "kind": "corrupt",
             "keep_frac": 0.4},))) as inj:
        export.write_leaf_table(di_clean.tree, d)
    assert inj.n_fired() == 1
    with pytest.raises(atomic.CorruptArtifact):
        export.load_leaf_table(d)


def test_save_artifacts_commit_marker_ordering(tmp_path, di_clean,
                                               monkeypatch):
    """A crash between the leaf-table export and the descent write
    must leave an UNCOMMITTED directory (no meta.json) -- never a
    'valid' table next to a missing/stale descent.npz."""
    from explicit_hybrid_mpc_tpu.online import descent as descent_mod
    from explicit_hybrid_mpc_tpu.serve.registry import (ControllerRegistry,
                                                        save_artifacts)

    d = str(tmp_path / "v")

    def boom(table, path):
        raise InjectedCrash("crash before descent landed")

    monkeypatch.setattr(descent_mod, "save_descent", boom)
    with pytest.raises(InjectedCrash):
        save_artifacts(di_clean.tree, di_clean.roots, d)
    assert not os.path.exists(os.path.join(d, "meta.json"))
    monkeypatch.undo()
    # Re-export into the SAME directory completes and loads cleanly
    # (the torn attempt left no stale commit marker to confuse it).
    save_artifacts(di_clean.tree, di_clean.roots, d)
    reg = ControllerRegistry()
    assert reg.load_artifacts("ctl", "v1", d).version == "v1"


def test_rebuild_rejects_corrupt_prior(tmp_path, di_clean, di_problem):
    from explicit_hybrid_mpc_tpu.partition.rebuild import (RebuildError,
                                                           warm_rebuild)

    p = str(tmp_path / "prior.tree.pkl")
    di_clean.tree.save(p)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(RebuildError, match="integrity"):
        warm_rebuild(di_problem, _cfg(), p)


# -- retry / quarantine / degrade ------------------------------------------

def test_device_failure_recovery_tree_parity(di_problem, di_clean):
    plan = FaultPlan(faults=(
        {"site": "oracle.dispatch", "kind": "error", "at": 2,
         "match": "primary"},
        {"site": "oracle.wait", "kind": "error", "at": 4},))
    with faults.activate(plan) as inj:
        res = build_partition(di_problem, _cfg())
    inj.assert_all_fired()
    assert res.stats["device_failures"] == 2
    assert res.stats["quarantined_cells"] == 0
    assert np.array_equal(di_clean.tree.vertices, res.tree.vertices)


def test_solve_hang_timeout_recovery(di_problem, di_clean):
    plan = FaultPlan(faults=(
        {"site": "oracle.wait", "kind": "hang", "at": 2,
         "hang_s": 5.0},))
    with faults.activate(plan) as inj:
        res = build_partition(di_problem, _cfg(solve_timeout_s=0.5))
    inj.assert_all_fired()
    assert res.stats["device_failures"] == 1
    assert res.stats["quarantined_cells"] == 0
    assert np.array_equal(di_clean.tree.vertices, res.tree.vertices)


def test_quarantine_on_exhausted_recovery(pend_problem):
    """Primary AND fallback scripted dead for one stage-2 call: the
    cells quarantine, the build survives, and the result is sound
    (only extra splitting / uncertified closures)."""
    plan = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "at": 1},
        {"site": "oracle.fallback", "kind": "error", "at": 1,
         "count": 2},))
    cfg = _pend_cfg(oracle_retry_attempts=2, obs="jsonl")
    with faults.activate(plan) as inj:
        res = build_partition(pend_problem, cfg)
    inj.assert_all_fired()
    assert res.stats["quarantined_cells"] > 0
    assert not res.stats["truncated"]  # the build went to completion


def test_quarantine_emits_obs_counter(pend_problem):
    obs = obs_lib.Obs("jsonl")
    plan = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "at": 1},
        {"site": "oracle.fallback", "kind": "error", "at": 1,
         "count": 2},))
    with faults.activate(plan):
        res = build_partition(
            pend_problem, _pend_cfg(oracle_retry_attempts=2), obs=obs)
    snap = obs.flush_metrics()
    assert snap["counters"]["build.quarantined_cells"] \
        == res.stats["quarantined_cells"]
    assert snap["counters"]["faults.injected"] >= 2
    names = [r.get("name") for r in obs.sink.records]
    assert "faults.quarantine" in names and "faults.injected" in names


def test_device_degrade_cap(pend_problem, pend_clean):
    """A persistently failing device degrades the engine ONCE (cap +
    in-flight stragglers), not per-batch, and the twin finishes the
    identical tree."""
    plan = FaultPlan(faults=(
        {"site": "oracle.dispatch", "kind": "error", "at": 1,
         "count": 100000, "match": "primary"},))
    with faults.activate(plan):
        res = build_partition(
            pend_problem, _pend_cfg(device_failure_cap=3))
    assert res.stats["device_degraded"]
    # Bounded by cap + the handles already in flight at degrade time
    # -- nowhere near one failure per batch.
    assert 3 <= res.stats["device_failures"] <= 3 + 5
    assert res.stats["quarantined_cells"] == 0
    assert np.array_equal(pend_clean.tree.vertices, res.tree.vertices)


def test_retry_policy_validation():
    from explicit_hybrid_mpc_tpu.faults import RetryPolicy

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(solve_timeout_s=0)
    with pytest.raises(ValueError):
        PartitionConfig(eps_a=0.1, oracle_retry_attempts=0)
    with pytest.raises(ValueError):
        PartitionConfig(eps_a=0.1, solve_timeout_s=-1)
    assert RetryPolicy(backoff_s=0.1).backoff(2) == pytest.approx(0.4)


# -- serve: lease leak + publish atomicity ---------------------------------

def _dummy_server(di_clean, tmp_path, name):
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    d = str(tmp_path / name)
    save_artifacts(di_clean.tree, di_clean.roots, d)
    return d


def test_wait_retired_timeout_emits_lease_leak(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.serve.registry import ControllerRegistry

    obs = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=obs)
    d = _dummy_server(di_clean, tmp_path, "v1")
    v1 = reg.load_artifacts("ctl", "v1", d)
    # A thread that died holding a lease: enter without exiting.
    leak = reg.lease("ctl")
    leak.__enter__()
    reg.load_artifacts("ctl", "v2", d)     # v1 -> retiring, pinned
    assert not reg.wait_retired(v1, timeout=0.05)
    ev = [r for r in obs.sink.records
          if r.get("name") == "health.lease_leak"]
    assert ev and ev[-1]["value"] == 1 and ev[-1]["severity"] == "warn"
    # HealthMonitor adopts the event -> external watchers exit nonzero.
    from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor

    mon = HealthMonitor()
    mon.feed(ev[-1])
    assert mon.worst == "warn"
    leak.__exit__(None, None, None)
    assert reg.wait_retired(v1, timeout=1.0)


def test_publish_injection_leaves_registry_intact(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.serve.registry import ControllerRegistry

    reg = ControllerRegistry()
    d = _dummy_server(di_clean, tmp_path, "v1")
    reg.load_artifacts("ctl", "v1", d)
    with faults.activate(FaultPlan(faults=(
            {"site": "registry.publish", "kind": "error"},))):
        with pytest.raises(InjectedFault):
            reg.load_artifacts("ctl", "v2", d)
    assert reg.active_version("ctl") == "v1"
    with reg.lease("ctl") as ver:
        assert ver.version == "v1"


def test_scheduler_crash_mid_batch_releases_lease(tmp_path, di_clean):
    """An injected serve.batch crash inside the leased evaluation
    fails the tickets but NEVER pins the version (lease released in
    the context manager's finally)."""
    from explicit_hybrid_mpc_tpu.serve.registry import ControllerRegistry
    from explicit_hybrid_mpc_tpu.serve.scheduler import RequestScheduler

    reg = ControllerRegistry()
    d = _dummy_server(di_clean, tmp_path, "v1")
    v1 = reg.load_artifacts("ctl", "v1", d)
    sched = RequestScheduler(reg, "ctl", max_batch=8, max_wait_us=500.0)
    with faults.activate(FaultPlan(faults=(
            {"site": "serve.batch", "kind": "crash"},))):
        t = sched.submit(np.zeros(v1.server.root_bary.shape[-1] - 1))
        with pytest.raises(InjectedCrash):
            t.result(timeout=5.0)
    reg.load_artifacts("ctl", "v2", d)
    assert reg.wait_retired(v1, timeout=5.0)  # v1 drained, not pinned
    sched.close()


# -- health rule + sink durability -----------------------------------------

def test_max_quarantine_frac_rule():
    from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor

    mon = HealthMonitor({"max_quarantine_frac": 0.01,
                         "min_solves_for_rates": 100})
    ev = mon.feed({"kind": "metrics",
                   "counters": {"build.quarantined_cells": 50,
                                "oracle.point_solves": 1000,
                                "oracle.simplex_solves": 0},
                   "gauges": {}})
    assert [e["name"] for e in ev] == ["health.quarantine"]
    assert mon.worst == "critical"
    # volume gate: tiny runs never trip it
    mon2 = HealthMonitor({"max_quarantine_frac": 0.01,
                          "min_solves_for_rates": 2000})
    assert not mon2.feed({"kind": "metrics",
                          "counters": {"build.quarantined_cells": 5,
                                       "oracle.point_solves": 50},
                          "gauges": {}})
    # 0 disables
    mon3 = HealthMonitor({"max_quarantine_frac": 0,
                          "min_solves_for_rates": 10})
    assert not mon3.feed({"kind": "metrics",
                          "counters": {"build.quarantined_cells": 500,
                                       "oracle.point_solves": 100},
                          "gauges": {}})


def test_sink_fsync_every(tmp_path):
    from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink, load_jsonl

    p = str(tmp_path / "s.jsonl")
    with JsonlSink(p, fsync_every=2) as s:
        for i in range(5):
            s.emit("event", "e", i=i)
    assert len(load_jsonl(p)) == 5


def test_obs_report_renders_faults_block(tmp_path, pend_problem):
    import importlib.util
    import sys as _sys

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "obs_report.py"))
    obs_report = importlib.util.module_from_spec(spec)
    _sys.modules["obs_report"] = spec.loader.exec_module(obs_report) \
        or obs_report
    path = str(tmp_path / "s.obs.jsonl")
    obs = obs_lib.Obs("jsonl", path=path)
    plan = FaultPlan(faults=(
        {"site": "oracle.call", "kind": "error", "at": 1},
        {"site": "oracle.fallback", "kind": "error", "at": 1,
         "count": 2},))
    with faults.activate(plan):
        build_partition(pend_problem,
                        _pend_cfg(oracle_retry_attempts=2), obs=obs)
    obs.flush_metrics()
    obs.close(snapshot=False)
    rep = obs_report.report(obs_report.load_jsonl(path))
    assert rep["faults"]["quarantined_cells"] > 0
    assert rep["faults"]["injected"] >= 2
    text = obs_report.render_text(rep, [], None)
    assert "faults:" in text and "quarantined" in text
    assert any("quarantined" in w for w in rep.get("warnings", []))


def test_bench_gate_append_history_durable(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "bench_gate.py"))
    bench_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_gate)
    hist = str(tmp_path / "H.jsonl")
    row = bench_gate.append_history(
        {"value": 1.0, "platform": "cpu", "metric": "r/s",
         "quarantined_cells": 0}, "BENCH_x.json", path=hist, mtime=1.0)
    assert row is not None and row["quarantined_cells"] == 0
    assert bench_gate.load_history(hist)[0]["value"] == 1.0
    # dupe key skipped
    assert bench_gate.append_history(
        {"value": 1.0, "platform": "cpu"}, "BENCH_x.json", path=hist,
        mtime=1.0) is None


def test_tree_save_checksummed_load_rejects_corrupt(tmp_path, di_clean):
    from explicit_hybrid_mpc_tpu.partition.tree import Tree

    p = str(tmp_path / "t.tree.pkl")
    di_clean.tree.save(p)
    t2 = Tree.load(p)
    assert np.array_equal(di_clean.tree.vertices, t2.vertices)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 7)
    with pytest.raises(atomic.CorruptArtifact):
        Tree.load(p)


def test_config_fault_plan_threading(tmp_path, di_problem, di_clean):
    """cfg.fault_plan (a path) installs the injector inside
    build_partition -- the CLI/EHM_FAULT_PLAN surface, minus the
    subprocess."""
    plan_p = str(tmp_path / "plan.json")
    FaultPlan(faults=(
        {"site": "oracle.wait", "kind": "error", "at": 1},)).save(plan_p)
    try:
        res = build_partition(di_problem, _cfg(fault_plan=plan_p))
    finally:
        faults.clear()
    assert res.stats["device_failures"] == 1
    assert np.array_equal(di_clean.tree.vertices, res.tree.vertices)


def test_concurrent_fire_thread_safety():
    plan = FaultPlan(faults=(
        {"site": "serve.batch", "kind": "error", "at": 500},))
    with faults.activate(plan) as inj:
        errs = []

        def worker():
            for _ in range(100):
                try:
                    faults.fire("serve.batch")
                except InjectedFault as e:
                    errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert inj.count("serve.batch") == 800
        assert len(errs) == 1  # exactly the scripted occurrence
