"""Multi-process scale-out: 2 jax.distributed processes on localhost CPU
drive the same frontier build with vertex-grid solves sharded over the
GLOBAL device mesh (SURVEY.md section 6.8; round-1 verdict item 5 -- the
multi-host path must stage process-local arrays and be tested, not be a
pass-through stub)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_build_matches_single_process():
    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
    from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
    from explicit_hybrid_mpc_tpu.problems.registry import make

    # Reference: single-process build of the identical problem/config.
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=32, max_depth=20)
    ref = build_partition(prob, cfg, Oracle(prob, backend="cpu"))

    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join("tests", "_mp_worker.py"),
         str(port), str(i), "2"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert by_pid[0]["owner"] and not by_pid[1]["owner"]
    # Both processes ran the frontier in lockstep: identical trees.
    for k in ("regions", "tree_nodes", "max_depth", "oracle_solves"):
        assert by_pid[0][k] == by_pid[1][k], k
    # And the distributed build matches the single-process ground truth.
    assert by_pid[0]["regions"] == ref.stats["regions"]
    assert by_pid[0]["tree_nodes"] == ref.stats["tree_nodes"]
    assert by_pid[0]["max_depth"] == ref.stats["max_depth"]


def test_stage_batch_single_process_roundtrip():
    """stage_batch/stage_replicated: single-process path is a device_put
    that the mesh solver consumes unchanged."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from explicit_hybrid_mpc_tpu.parallel import distributed, make_mesh

    mesh = make_mesh((4, 2))
    x = np.arange(32, dtype=np.float64).reshape(8, 4)
    arr = distributed.stage_batch(NamedSharding(mesh, P("batch")), x)
    np.testing.assert_array_equal(np.asarray(arr), x)
    m = np.arange(6) < 4
    rep = distributed.stage_replicated(NamedSharding(mesh, P("delta")), m)
    np.testing.assert_array_equal(np.asarray(rep), m)
    assert isinstance(arr, jax.Array)
