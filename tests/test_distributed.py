"""Multi-process scale-out: 2 jax.distributed processes on localhost CPU
drive the same frontier build with vertex-grid solves sharded over the
GLOBAL device mesh (SURVEY.md section 6.8; round-1 verdict item 5 -- the
multi-host path must stage process-local arrays and be tested, not be a
pass-through stub)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_build_matches_single_process():
    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
    from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
    from explicit_hybrid_mpc_tpu.problems.registry import make

    # Reference: single-process build of the identical problem/config.
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=32, max_depth=20)
    ref = build_partition(prob, cfg, Oracle(prob, backend="cpu"))

    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join("tests", "_mp_worker.py"),
         str(port), str(i), "2"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert by_pid[0]["owner"] and not by_pid[1]["owner"]
    # Both processes ran the frontier in lockstep: identical trees.
    for k in ("regions", "tree_nodes", "max_depth", "oracle_solves"):
        assert by_pid[0][k] == by_pid[1][k], k
    # And the distributed build matches the single-process ground truth.
    assert by_pid[0]["regions"] == ref.stats["regions"]
    assert by_pid[0]["tree_nodes"] == ref.stats["tree_nodes"]
    assert by_pid[0]["max_depth"] == ref.stats["max_depth"]


def test_local_contiguous_block_predicate():
    """The explicit stage_batch fast-path predicate: dim-0-only,
    equal-sized, gap-free runs pass; every other layout -- permuted
    device order, trailing-dim sharding, ragged blocks -- must route
    to the callback fallback (returns None)."""
    from explicit_hybrid_mpc_tpu.parallel.distributed import (
        local_contiguous_block)

    shape = (8, 4)
    ok = {0: (slice(0, 2), slice(None)), 1: (slice(2, 4), slice(None))}
    assert local_contiguous_block(ok, shape) == (0, 4)
    full_stop = {0: (slice(0, 4), slice(0, 4)),
                 1: (slice(4, 8), slice(0, 4))}
    assert local_contiguous_block(full_stop, shape) == (0, 8)
    # Interleaved local rows (permuted global device order).
    gap = {0: (slice(0, 2), slice(None)), 1: (slice(4, 6), slice(None))}
    assert local_contiguous_block(gap, shape) is None
    # REPLICATED blocks (a (batch, delta) mesh under P("batch"): every
    # local delta-axis device holds the same dim-0 slice) stay on the
    # fast path -- duplicates are replication, not overlap.
    repl = {0: (slice(0, 4), slice(None)), 1: (slice(0, 4), slice(None)),
            2: (slice(4, 8), slice(None)), 3: (slice(4, 8), slice(None))}
    assert local_contiguous_block(repl, shape) == (0, 8)
    # Trailing-dim sharding: the local block is NOT a dim-0 slice of
    # the host-global array (the old heuristic could pass this).
    trailing = {0: (slice(0, 8), slice(0, 2)),
                1: (slice(0, 8), slice(2, 4))}
    assert local_contiguous_block(trailing, shape) is None
    # Ragged per-device blocks.
    ragged = {0: (slice(0, 3), slice(None)), 1: (slice(3, 4), slice(None))}
    assert local_contiguous_block(ragged, shape) is None
    # Strided slices never qualify.
    strided = {0: (slice(0, 8, 2), slice(None))}
    assert local_contiguous_block(strided, shape) is None
    assert local_contiguous_block({}, shape) is None


def test_two_process_stage_batch_permuted_mesh():
    """Multi-process semantics of the contiguity fix: a mesh built
    from an interleaved global device list gives every process
    non-contiguous local rows; stage_batch must reject the fast path
    (contiguous_block None) and the callback fallback must stage every
    shard's exact rows."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join("tests", "_mp_worker.py"),
         str(port), str(i), "2", "stage_permuted"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for i in range(2)]
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("permuted-mesh worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        rec = json.loads(out.strip().splitlines()[-1])
        assert rec["ok"], rec
        assert rec["contiguous_block"] is None, rec
        assert rec["n_local_shards"] == 4


def test_stage_batch_single_process_roundtrip():
    """stage_batch/stage_replicated: single-process path is a device_put
    that the mesh solver consumes unchanged."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from explicit_hybrid_mpc_tpu.parallel import distributed, make_mesh

    mesh = make_mesh((4, 2))
    x = np.arange(32, dtype=np.float64).reshape(8, 4)
    arr = distributed.stage_batch(NamedSharding(mesh, P("batch")), x)
    np.testing.assert_array_equal(np.asarray(arr), x)
    m = np.arange(6) < 4
    rep = distributed.stage_replicated(NamedSharding(mesh, P("delta")), m)
    np.testing.assert_array_equal(np.asarray(rep), m)
    assert isinstance(arr, jax.Array)
