"""Device-resident multi-tenant arena (serve/arena.py) + the mixed-
tenant ArenaScheduler (serve/scheduler.py): directory lifecycle,
two-epoch hot swap, O(changed) delta publish (bitwise vs a full
re-pack), launch fusion, and kernel-path vs host-path fallback-counter
reconciliation."""

import os
import threading

import numpy as np
import pytest

import explicit_hybrid_mpc_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.online import evaluator, export
from explicit_hybrid_mpc_tpu.serve import (ArenaFull, ArenaScheduler,
                                           DeviceArena, FallbackPolicy)


def _synthetic_table(rng, L=40, p=2, n_u=2):
    """Disjoint unit-grid simplices (test_pallas_fused idiom)."""
    from explicit_hybrid_mpc_tpu.partition import geometry

    base = np.vstack([np.zeros(p), np.eye(p)])
    side = int(np.ceil(np.sqrt(L)))
    bary, U, V = [], [], []
    for i in range(L):
        off = np.array([i % side, i // side], dtype=float)[:p]
        verts = 0.8 * base + off + 0.1 * rng.uniform(size=p)
        bary.append(geometry.barycentric_matrix(verts))
        U.append(rng.normal(size=(p + 1, n_u)))
        V.append(np.abs(rng.normal(size=p + 1)))
    return export.LeafTable(
        bary_M=np.stack(bary), U=np.stack(U), V=np.stack(V),
        delta=np.zeros(L, dtype=np.int64),
        node_id=np.arange(L, dtype=np.int64))


def _centroids(table):
    return np.stack([np.linalg.inv(table.bary_M[i])[:-1, :].mean(axis=1)
                     for i in range(table.n_leaves)])


_BOX = (np.zeros(2), np.full(2, 8.0))


# -- directory / allocation -----------------------------------------------


def test_publish_stats_and_capacity(rng):
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, backend="xla")
    t = _synthetic_table(rng)
    arena.publish("a", "v1", t, *_BOX)
    arena.publish("b", "v1", _synthetic_table(rng, L=30), *_BOX)
    s = arena.stats()
    assert s["controllers"] == 2 and s["free_cols"] == 0
    assert s["versions"] == {"a": "v1", "b": "v1"}
    assert s["resident_bytes"] == 256 * arena._col_bytes()
    with pytest.raises(ArenaFull):
        arena.publish("c", "v1", _synthetic_table(rng, L=5), *_BOX)
    # Republishing the SAME (name, version) is a publisher bug, not a
    # swap: the directory must reject it rather than double-allocate.
    with pytest.raises(ValueError):
        arena.publish("a", "v1", t, *_BOX)
    # Retiring a tenant frees its columns for the next publish.
    arena.retire("a")
    assert arena.stats()["free_cols"] == 128
    arena.publish("c", "v1", _synthetic_table(rng, L=5), *_BOX)
    with pytest.raises(KeyError):
        arena.extent("a")
    with pytest.raises(KeyError):
        arena.evaluate("a", np.zeros((1, 2)))


def test_capacity_must_be_tile_multiple():
    with pytest.raises(ValueError):
        DeviceArena(p=2, n_u=2, capacity_cols=100)
    with pytest.raises(ValueError):
        DeviceArena(p=2, n_u=2, capacity_cols=0)


def test_theta_width_mismatch(rng):
    arena = DeviceArena(p=2, n_u=2, capacity_cols=128, backend="xla")
    arena.publish("a", "v1", _synthetic_table(rng), *_BOX)
    with pytest.raises(ValueError):
        arena.evaluate("a", np.zeros((2, 3)))
    with pytest.raises(ValueError):
        arena.evaluate(["a", "a", "a"], np.zeros((2, 2)))


# -- two-epoch hot swap ---------------------------------------------------


def test_two_epoch_handoff(rng):
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, backend="xla")
    t1, t2 = _synthetic_table(rng), _synthetic_table(rng)
    e1 = arena.publish("a", "v1", t1, *_BOX)
    with arena.lease(["a"]):
        arena.publish("a", "v2", t2, *_BOX)
        # The directory flips immediately; the leased old extent only
        # RETIRES -- its columns must not be reused under the reader.
        assert arena.extent("a").version == "v2"
        assert e1.state == "retiring"
        assert not arena.wait_retired(e1, timeout=0.05)
    assert e1.state == "retired"
    assert arena.wait_retired(e1, timeout=1.0)
    assert arena.stats()["retiring"] == 0
    # New queries land on v2's payloads.
    out = arena.evaluate("a", _centroids(t2)[:4])
    ref = evaluator.evaluate(evaluator.stage(t2),
                             jnp.asarray(_centroids(t2)[:4]))
    assert np.array_equal(out.leaf, np.asarray(ref.leaf))
    assert out.versions == {"a": "v2"}


def test_swap_without_reader_retires_immediately(rng):
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, backend="xla")
    e1 = arena.publish("a", "v1", _synthetic_table(rng), *_BOX)
    arena.publish("a", "v2", _synthetic_table(rng), *_BOX)
    assert e1.state == "retired" and arena.wait_retired(e1, 0.0)
    assert arena.stats()["free_cols"] == 128


# -- delta publish --------------------------------------------------------


def test_publish_delta_bitwise_and_o_changed(rng, tmp_path):
    from explicit_hybrid_mpc_tpu.lifecycle.delta import (
        DeltaMismatch, apply_delta, write_delta_artifact)
    from explicit_hybrid_mpc_tpu.partition.synthetic import \
        build_synthetic_tree
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    base_dir = str(tmp_path / "base")
    delta_dir = str(tmp_path / "delta")
    out_dir = str(tmp_path / "v2_full")
    tree1, roots1 = build_synthetic_tree(p=2, depth=6, n_u=2)
    # An unstamped base cannot anchor a delta (provenance gate), so
    # stamp the synthetic artifact explicitly.
    save_artifacts(tree1, roots1, base_dir,
                   provenance={"problem": "synthetic"})
    tree2, roots2 = build_synthetic_tree(p=2, depth=6, n_u=2)
    # Double HALF the (used) payload slots: exact in floating point,
    # and the delta stays O(changed) -- the untouched half must ride
    # as kept rows.  (_pl_inputs is a preallocated pool; only the
    # first _n_slots rows are live.)
    n_pl = tree2._n_slots
    tree2._pl_inputs[:n_pl // 2] *= 2.0
    tree2._pl_costs[:n_pl // 2] *= 2.0
    stats = write_delta_artifact(tree2, roots2, delta_dir, base_dir,
                                 base_version="v1")
    assert 0 < stats["n_fresh"] < stats["n_fresh"] + stats["n_kept"]
    assert stats["n_kept"] > 0

    arena = DeviceArena(p=2, n_u=2, capacity_cols=512, backend="xla")
    e1 = arena.publish_from_artifacts("c", "v1", base_dir)
    e2 = arena.publish_delta("c", "v2", delta_dir, base_dir)
    assert arena.extent("c").version == "v2"
    assert e2.n_leaves == e1.n_leaves

    # Bitwise contract: the delta-applied extent equals a FULL re-pack
    # of the reconstructed v2 table, column for column.
    apply_delta(delta_dir, base_dir, out_dir)
    ref = DeviceArena(p=2, n_u=2, capacity_cols=512, backend="xla")
    e_ref = ref.publish_from_artifacts("c", "v2", out_dir)
    sl = np.s_[e2.start:e2.end]
    rl = np.s_[e_ref.start:e_ref.end]
    assert np.array_equal(np.asarray(arena.bary[:, :, sl]),
                          np.asarray(ref.bary[:, :, rl]))
    assert np.array_equal(np.asarray(arena.U[:, sl, :]),
                          np.asarray(ref.U[:, rl, :]))
    assert np.array_equal(np.asarray(arena.V[:, sl]),
                          np.asarray(ref.V[:, rl]))

    # Wrong resident generation => DeltaMismatch, directory untouched.
    with pytest.raises(DeltaMismatch):
        arena.publish_delta("c", "v3", delta_dir, base_dir)
    with pytest.raises(DeltaMismatch):
        arena.publish_delta("nope", "v2", delta_dir, base_dir)
    assert arena.extent("c").version == "v2"


# -- mixed-tenant scheduler -----------------------------------------------


def test_arena_scheduler_mixed_batches(rng):
    o = obs_lib.Obs("jsonl")
    arena = DeviceArena(p=2, n_u=2, capacity_cols=512, backend="xla",
                        obs=o)
    tables = {}
    for k in range(3):
        tables[f"t{k}"] = _synthetic_table(rng, L=20 + 3 * k)
        arena.publish(f"t{k}", "v1", tables[f"t{k}"], *_BOX)
    fb = FallbackPolicy(*_BOX, obs=o)
    n_req = 36
    with ArenaScheduler(arena, max_batch=64, max_wait_us=20000.0,
                        fallback=fb, obs=o) as sched:
        names = [f"t{i % 3}" for i in range(n_req)]
        thetas = [_centroids(tables[nm])[i % 10] for i, nm
                  in enumerate(names)]
        tickets = [sched.submit(nm, th) for nm, th
                   in zip(names, thetas)]
        results = [t.result(30.0)[0] for t in tickets]
        # Launch fusion: 36 single-row submissions across 3 tenants in
        # a 20 ms wait window must coalesce -- strictly fewer launches
        # than requests (the tentpole's dispatch-count win).
        assert sched.n_requests == n_req
        assert sched.n_batches < n_req
        for nm, th, r in zip(names, thetas, results):
            ref = evaluator.evaluate(evaluator.stage(tables[nm]),
                                     jnp.asarray(th[None, :]))
            assert r.leaf == int(np.asarray(ref.leaf)[0])
            assert r.inside and r.version == "v1"
            assert r.fallback is None
            np.testing.assert_allclose(r.u, np.asarray(ref.u)[0],
                                       atol=1e-5)
        snap = o.metrics.snapshot()["counters"]
        assert snap.get("serve.requests") == n_req
        assert snap.get("serve.batches") == sched.n_batches
        assert sum(snap.get(f"serve.ctl.t{k}.requests", 0)
                   for k in range(3)) == n_req
        assert snap.get("serve.arena.launches", 0) == sched.n_batches
        with pytest.raises(KeyError):
            sched.submit("ghost", np.zeros(2))
        with pytest.raises(ValueError):
            sched.submit("t0", np.zeros(3))
    with pytest.raises(RuntimeError):
        sched.submit("t0", np.zeros(2))
    o.close()


def test_arena_scheduler_pow2_validation(rng):
    arena = DeviceArena(p=2, n_u=2, capacity_cols=128, backend="xla")
    arena.publish("a", "v1", _synthetic_table(rng), *_BOX)
    with pytest.raises(ValueError):
        ArenaScheduler(arena, max_batch=48)
    with pytest.raises(ValueError):
        ArenaScheduler(arena, max_wait_us=0.0)


def test_scheduler_swap_during_traffic(rng):
    """Requests racing a hot swap: nothing drops, every row is tagged
    with the version it actually evaluated on, and the old extent
    drains (two-epoch under real traffic)."""
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, backend="xla")
    t1, t2 = _synthetic_table(rng), _synthetic_table(rng)
    e1 = arena.publish("a", "v1", t1, *_BOX)
    cents = _centroids(t1)
    with ArenaScheduler(arena, max_batch=8, max_wait_us=500.0) as sched:
        tickets, stop = [], threading.Event()

        def pump():
            for i in range(200):
                tickets.append(sched.submit("a", cents[i % 40]))
            stop.set()

        th = threading.Thread(target=pump)
        th.start()
        arena.publish("a", "v2", t2, *_BOX)
        th.join()
        results = [t.result(30.0)[0] for t in tickets]
    versions = {r.version for r in results}
    assert versions <= {"v1", "v2"} and "v2" in versions
    assert all(r.inside for r in results)
    assert arena.wait_retired(e1, timeout=10.0)


# -- fallback reconciliation ----------------------------------------------


class _HostServer:
    """Minimal host-path server shim for FallbackPolicy.apply: the f64
    evaluator with no root_bary (the policy then clamps to its
    constructor box, same box the arena rows carry)."""

    root_bary = None

    def __init__(self, table):
        self._dev = evaluator.stage(table)

    def evaluate(self, thetas):
        return evaluator.evaluate(self._dev, jnp.asarray(thetas))


def test_fallback_counters_reconcile_kernel_vs_host(rng):
    """THE satellite contract: on the same query mix, the kernel path
    (arena clamp + account_kernel) and the host path (f64 evaluate +
    FallbackPolicy.apply) must land identical serve.fallback.* counter
    values and identical per-row tags."""
    table = _synthetic_table(rng, L=40)
    cents = _centroids(table)
    # Box whose upper corner IS a cell centroid: far-out queries clamp
    # exactly onto a covered point, so the clamp outcome is decided
    # identically by both paths (no knife-edge geometry).
    lb = np.zeros(2)
    ub = cents[np.argmax(cents.sum(axis=1))]
    thetas = np.concatenate([
        cents[:6],                          # served in place
        np.array([[0.95, 0.95],             # in-box uncovered: holes
                  [1.95, 2.95]]),
        ub + np.array([[2.0, 3.0],          # outside -> clamp to ub
                       [5.0, 0.5]]),        #   (a covered centroid)
        np.array([[-1.0, 0.95]]),           # outside -> clamp lands in
    ])                                      #   an uncovered gap
    thetas[-1] = np.array([-1.0, 0.95])

    o_k = obs_lib.Obs("jsonl")
    arena = DeviceArena(p=2, n_u=2, capacity_cols=128, backend="xla",
                        obs=o_k)
    arena.publish("a", "v1", table, lb, ub)
    fb_k = FallbackPolicy(lb, ub, obs=o_k)
    res = arena.evaluate("a", thetas)
    tags_k = fb_k.account_kernel(res.clamped, res.served)

    o_h = obs_lib.Obs("jsonl")
    fb_h = FallbackPolicy(lb, ub, obs=o_h)
    server = _HostServer(table)
    raw = server.evaluate(thetas)
    patched, tags_h = fb_h.apply(thetas, raw, server)

    assert tags_k == tags_h
    assert fb_k.n_seen == fb_h.n_seen == thetas.shape[0]
    ck = o_k.metrics.snapshot()["counters"]
    ch = o_h.metrics.snapshot()["counters"]
    for key in ("outside_box", "hole", "clamp", "unserved",
                "requests"):
        assert ck.get(f"serve.fallback.{key}", 0) == \
            ch.get(f"serve.fallback.{key}", 0), key
    # And the mix genuinely exercised every class.
    assert ck["serve.fallback.outside_box"] == 3
    assert ck["serve.fallback.hole"] == 2
    assert ck["serve.fallback.clamp"] == 2
    assert ck["serve.fallback.unserved"] == 3
    # Served clamped rows carry the clamped point's law on both paths.
    clamp_rows = [i for i, t in enumerate(tags_k) if t == "clamp"]
    np.testing.assert_allclose(
        res.u[clamp_rows, :2], np.asarray(patched.u)[clamp_rows],
        atol=1e-5)
    o_k.close()
    o_h.close()


def test_fallback_mode_off_counts_nothing(rng):
    o = obs_lib.Obs("jsonl")
    fb = FallbackPolicy(*_BOX, mode="off", obs=o)
    tags = fb.account_kernel(np.array([True, False]),
                             np.array([False, True]))
    assert tags == [None, None] and fb.n_seen == 2
    snap = o.metrics.snapshot()["counters"]
    assert snap.get("serve.fallback.requests", 0) == 0
    o.close()


# -- obs_report integration -----------------------------------------------


def _load_script(name):
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_arena_block(rng, tmp_path):
    """The arena's obs stream assembles into rep['arena'], renders the
    `arena:` / `arena swap:` lines, and both new bench metrics
    diff-flag directionally."""
    path = str(tmp_path / "arena.obs.jsonl")
    o = obs_lib.Obs("jsonl", path=path)
    arena = DeviceArena(p=2, n_u=2, capacity_cols=384, backend="xla",
                        obs=o)
    tables = {n: _synthetic_table(rng, L=20) for n in ("a", "b")}
    for n, t in tables.items():
        arena.publish(n, "v1", t, *_BOX)
    with ArenaScheduler(arena, max_batch=8, max_wait_us=20000.0,
                        obs=o) as sched:
        tickets = [sched.submit(n, _centroids(tables[n])[i % 5])
                   for i, n in enumerate(["a", "b"] * 8)]
        for t in tickets:
            t.result(30.0)
    arena.publish("a", "v2", tables["a"], *_BOX)  # third swap_us sample
    o.flush_metrics()
    o.close()
    from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl

    obs_report = _load_script("obs_report")
    rep = obs_report.report(load_jsonl(path))
    ar = rep["arena"]
    assert ar["controllers"] == 2
    assert ar["publishes"] == 3
    assert ar["launches"] >= 1
    assert ar["swap_us"]["count"] == 3
    assert ar["resident_bytes"] > 0
    assert ar["launches_per_req"] <= 1.0
    txt = obs_report.render_text(rep, [], None)
    assert "arena:" in txt and "arena swap:" in txt
    # Directional regression flags vs a (better) bench row.
    flags = obs_report.diff_bench(
        rep, {"arena_swap_us": ar["swap_us"]["p99"] / 10,
              "batch_launches_per_req": 1e-4})
    assert any("arena swap regression" in f for f in flags)
    assert any("launch-amortization" in f for f in flags)
    # And a bench row this run BEATS raises no arena flags.
    flags_ok = obs_report.diff_bench(
        rep, {"arena_swap_us": ar["swap_us"]["p99"] * 10,
              "batch_launches_per_req": 2.0})
    assert not any("arena" in f for f in flags_ok)
