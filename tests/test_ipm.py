import jax
import jax.numpy as jnp
import functools

import numpy as np
from scipy.optimize import minimize

import explicit_hybrid_mpc_tpu  # noqa: F401  (enables x64)
from explicit_hybrid_mpc_tpu.oracle import ipm


def _scipy_qp(Q, q, A, b):
    n = Q.shape[0]
    res = minimize(
        lambda z: 0.5 * z @ Q @ z + q @ z, np.zeros(n),
        jac=lambda z: Q @ z + q, method="SLSQP",
        constraints=[{"type": "ineq", "fun": lambda z: b - A @ z,
                      "jac": lambda z: -A}],
        options={"ftol": 1e-12, "maxiter": 300})
    assert res.success
    return res.x, res.fun


def test_box_projection_analytic(rng):
    n = 6
    Q = jnp.eye(n)
    A = jnp.concatenate([jnp.eye(n), -jnp.eye(n)])
    b = jnp.ones(2 * n)
    a = rng.normal(size=(32, n)) * 2.0
    sol = jax.jit(jax.vmap(lambda q: ipm.qp_solve(Q, q, A, b)))(jnp.asarray(-a))
    np.testing.assert_allclose(np.asarray(sol.z), np.clip(a, -1, 1),
                               atol=1e-8)
    assert bool(np.all(sol.converged))


def test_random_qp_matches_scipy():
    # Local fixed-seed rng: the shared session fixture makes the draw
    # depend on which OTHER test files ran first, and a shifted stream
    # can produce an infeasible instance for this test's assumptions.
    rng = np.random.default_rng(7)
    for n, m in [(3, 5), (8, 20), (15, 40)]:
        M = rng.normal(size=(n, n))
        Q = M @ M.T + np.eye(n)
        q = rng.normal(size=n)
        A = rng.normal(size=(m, n))
        b = np.abs(rng.normal(size=m)) + 0.5  # z=0 strictly feasible
        sol = ipm.qp_solve(jnp.asarray(Q), jnp.asarray(q), jnp.asarray(A),
                           jnp.asarray(b))
        z_ref, f_ref = _scipy_qp(Q, q, A, b)
        assert bool(sol.converged)
        assert abs(float(sol.obj) - f_ref) < 1e-6 * (1 + abs(f_ref))
        np.testing.assert_allclose(np.asarray(sol.z), z_ref, atol=1e-5)


def test_active_constraint_duals(rng):
    # min 1/2 z^2 - z  s.t. z <= 0  ->  z*=0, lam*=1 (dual of the bound).
    sol = ipm.qp_solve(jnp.eye(1), -jnp.ones(1), jnp.ones((1, 1)),
                       jnp.zeros(1))
    assert abs(float(sol.z[0])) < 1e-8
    assert abs(float(sol.lam[0]) - 1.0) < 1e-6


def test_infeasible_detected():
    A = jnp.array([[1.0], [-1.0]])
    b = jnp.array([-1.0, -1.0])  # z <= -1 and z >= 1: empty
    sol = ipm.qp_solve(jnp.eye(1), jnp.zeros(1), A, b)
    assert not bool(sol.feasible)
    assert not bool(sol.converged)


def test_phase1_sign():
    A = jnp.array([[1.0], [-1.0]])
    t_inf = ipm.phase1(A, jnp.array([-1.0, -1.0]))   # empty set
    t_feas = ipm.phase1(A, jnp.array([1.0, 1.0]))    # [-1, 1]
    assert float(t_inf) > 0.5
    assert float(t_feas) < -0.5


def test_mixed_precision_matches_f64():
    """The f32-bulk + f64-polish schedule must reach the same KKT
    tolerance and objective as cold f64 (SURVEY.md section 8 "hard parts"
    item 2; schedule constants from Oracle(precision='mixed')).  Local
    fixed seed: the shared session fixture's stream depends on test
    order, and a rare marginal instance can miss the 1e-8 convergence
    flag by a hair."""
    rng = np.random.default_rng(0)
    N, nz, nc = 64, 12, 40
    Qs, qs, As, bs = [], [], [], []
    for _ in range(N):
        W = rng.normal(size=(nz, nz))
        Qs.append(W @ W.T + np.eye(nz))
        qs.append(rng.normal(size=nz))
        As.append(rng.normal(size=(nc, nz)))
        bs.append(np.abs(rng.normal(size=nc)) + 0.5)
    Qs, qs, As, bs = (jnp.asarray(np.stack(x)) for x in (Qs, qs, As, bs))
    ref = jax.jit(jax.vmap(functools.partial(
        ipm.qp_solve, n_iter=30)))(Qs, qs, As, bs)
    mix = jax.jit(jax.vmap(functools.partial(
        ipm.qp_solve, n_iter=10, n_f32=20)))(Qs, qs, As, bs)
    assert bool(ref.converged.all()) and bool(mix.converged.all())
    np.testing.assert_allclose(np.asarray(mix.obj), np.asarray(ref.obj),
                               rtol=1e-7, atol=1e-9)


def test_mixed_precision_infeasible_still_detected():
    A = jnp.array([[1.0], [-1.0]])
    b = jnp.array([-1.0, -1.0])  # empty
    sol = ipm.qp_solve(jnp.eye(1), jnp.zeros(1), A, b, n_iter=10, n_f32=20)
    assert not bool(sol.feasible) and not bool(sol.converged)


def test_mask_solver_cache_keying():
    """Regression guard for the PR 5 fix: _mask_solver is lru_cached
    on the FULL schedule key.  Identical (n_iter, n_f32, tol, kernel)
    tuples must hit the cache (same callable object -- rebuilding a
    jax.jit wrapper per call is the recompile hazard tpulint caught);
    nearby-but-distinct float tolerances, and distinct kernel tiers,
    must mint DISTINCT solvers (a shared one would silently solve at
    the wrong tolerance / through the wrong tier)."""
    a = ipm._mask_solver(12, 0, 1e-8, "xla")
    assert ipm._mask_solver(12, 0, 1e-8, "xla") is a
    assert ipm._mask_solver(12, 0, 1e-8 * (1 + 1e-12), "xla") is not a
    assert ipm._mask_solver(12, 0, 2e-8, "xla") is not a
    assert ipm._mask_solver(12, 0, 1e-8, "pallas") is not a
    assert ipm._mask_solver(13, 0, 1e-8, "xla") is not a


def test_degenerate_equality_like(rng):
    # Paired inequalities pin z1 = 0.3 exactly (empty interior): the IPM
    # must still converge (infeasible-start handles degenerate geometry).
    n = 3
    Q = jnp.eye(n)
    q = jnp.asarray(rng.normal(size=n))
    e = np.zeros((1, n)); e[0, 0] = 1.0
    A = jnp.asarray(np.vstack([e, -e]))
    b = jnp.asarray(np.array([0.3, -0.3]))
    sol = ipm.qp_solve(Q, q, A, b, n_iter=50)
    assert abs(float(sol.z[0]) - 0.3) < 1e-6
    np.testing.assert_allclose(np.asarray(sol.z[1:]),
                               -np.asarray(q)[1:], atol=1e-6)
