"""Two-phase early-exit cohort + tree warm-start tests (ISSUE 3).

Covers: the kernel's merit-gated warm_start path (rejection is bitwise
cold; continuation reaches full-schedule quality), exact iteration
accounting under the cohort (phase1 x cells + phase2 x survivors),
mixed-precision composition (f32_ok semantics unchanged), build-level
tree identity of the two-phase path, warm-start acceptance in a real
build, and the "warm shapes == run shapes" compiled-shape guard.
"""

import jax.numpy as jnp
import numpy as np

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle import ipm
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import (build_partition,
                                                        make_oracle)
from explicit_hybrid_mpc_tpu.problems.registry import make


def _rand_qp(seed, nz=8, nc=20):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(nz, nz))
    Q = W @ W.T + np.eye(nz)
    q = rng.normal(size=nz)
    A = rng.normal(size=(nc, nz))
    b = np.abs(rng.normal(size=nc)) + 0.5  # z=0 strictly feasible
    return tuple(jnp.asarray(x) for x in (Q, q, A, b))


# -- kernel-level warm-start semantics ---------------------------------------


def test_invalid_warm_start_is_bitwise_cold():
    """valid=False must be indistinguishable from no warm start at all
    (the cold trajectory is selected cell-exactly)."""
    Q, q, A, b = _rand_qp(0)
    cold = ipm.qp_solve(Q, q, A, b)
    warm = (jnp.ones(Q.shape[0]), jnp.ones(A.shape[0]),
            jnp.ones(A.shape[0]), jnp.asarray(False))
    gated = ipm.qp_solve(Q, q, A, b, warm_start=warm)
    assert not bool(gated.warm_ok)
    np.testing.assert_array_equal(np.asarray(cold.z), np.asarray(gated.z))
    np.testing.assert_array_equal(np.asarray(cold.lam),
                                  np.asarray(gated.lam))
    assert bool(cold.converged) == bool(gated.converged)


def test_bad_warm_start_rejected_by_merit_gate():
    """A garbage warm start (huge primal, boundary slacks) has worse
    merit than the cold start: the gate must reject it and the result
    must equal the cold solve of the same length, bitwise."""
    Q, q, A, b = _rand_qp(1)
    bad = (1e6 * jnp.ones(Q.shape[0]), 1e-9 * jnp.ones(A.shape[0]),
           1e6 * jnp.ones(A.shape[0]), jnp.asarray(True))
    got = ipm.qp_solve(Q, q, A, b, n_iter=8, warm_start=bad)
    ref = ipm.qp_solve(Q, q, A, b, n_iter=8)
    assert not bool(got.warm_ok)
    np.testing.assert_array_equal(np.asarray(got.z), np.asarray(ref.z))


def test_two_phase_continuation_reaches_full_schedule():
    """phase1(18) + merit-gated warm phase2(12) must reach what a cold
    30-iteration solve reaches (the cohort's correctness argument)."""
    Q, q, A, b = _rand_qp(2, nz=10, nc=30)
    full = ipm.qp_solve(Q, q, A, b, n_iter=30)
    p1 = ipm.qp_solve(Q, q, A, b, n_iter=18)
    p2 = ipm.qp_solve(Q, q, A, b, n_iter=12,
                      warm_start=(p1.z, p1.s, p1.lam, jnp.asarray(True)))
    assert bool(full.converged) and bool(p2.converged)
    assert bool(p2.warm_ok)
    f = float(full.obj)
    assert abs(float(p2.obj) - f) < 1e-7 * (1 + abs(f))


def test_f32_semantics_unchanged_under_warm_composition():
    """Satellite: f32_ok keeps its meaning when mixed precision composes
    with the warm path -- an invalid warm start plus the mixed schedule
    is bitwise the plain mixed schedule."""
    Q, q, A, b = _rand_qp(3, nz=12, nc=40)
    mix = ipm.qp_solve(Q, q, A, b, n_iter=10, n_f32=20)
    warm0 = (jnp.zeros(Q.shape[0]), jnp.zeros(A.shape[0]),
             jnp.zeros(A.shape[0]), jnp.asarray(False))
    mix2 = ipm.qp_solve(Q, q, A, b, n_iter=10, n_f32=20, warm_start=warm0)
    assert bool(mix.converged)
    assert bool(mix2.f32_ok) == bool(mix.f32_ok)
    assert not bool(mix2.warm_ok)
    np.testing.assert_array_equal(np.asarray(mix.z), np.asarray(mix2.z))


# -- oracle-level cohort + accounting ----------------------------------------


def test_two_phase_oracle_matches_single_phase_grid():
    prob = make("inverted_pendulum", N=2)
    rng = np.random.default_rng(4)
    th = rng.uniform(prob.theta_lb, prob.theta_ub, size=(12, 2))
    base = Oracle(prob, backend="cpu")
    tp = Oracle(prob, backend="cpu", two_phase=True)
    sb, st = base.solve_vertices(th), tp.solve_vertices(th)
    np.testing.assert_array_equal(sb.conv, st.conv)
    np.testing.assert_array_equal(sb.dstar, st.dstar)
    c = sb.conv
    np.testing.assert_allclose(st.V[c], sb.V[c], atol=1e-7)
    # The cohort actually engaged and saved f64 work.
    nd = prob.canonical.n_delta
    assert tp.n_tp_cells == 12 * nd
    # Diverged-cell early exit keeps the survivor set well below the
    # cell count (most unconverged cells are diverging-infeasible).
    assert tp.n_tp_survivors < tp.n_tp_cells
    assert tp.n_iters_f64 < tp.n_iters_f64_fixed
    assert base.n_iters_f64 == base.n_iters_f64_fixed
    # Full-output path returns the warm-start donor data.
    assert st.lam is not None and st.lam.shape == (12, nd,
                                                   prob.canonical.nc)


def test_exact_iteration_accounting_mixed_two_phase():
    """Satellite: oracle.ipm_iters == phase1 schedule x cells + phase2
    length x survivors, exactly, with mixed precision composed in."""
    prob = make("inverted_pendulum", N=2)
    o = obs_lib.Obs("jsonl")
    orc = Oracle(prob, backend="cpu", precision="mixed", n_f32=20,
                 two_phase=True, warm_start=True, obs=o)
    rng = np.random.default_rng(5)
    th = rng.uniform(prob.theta_lb, prob.theta_ub, size=(9, 2))
    orc.solve_vertices(th)
    nd = prob.canonical.n_delta
    N = 9 * nd
    assert orc.n_tp_cells == N
    assert orc.n_iters_f32 == N * orc.point_n_f32
    assert orc.n_iters_f64 == (N * orc.point_p1
                               + orc.n_tp_survivors * orc.point_p2)
    assert orc.n_iters_f64_fixed == N * orc.point_n_iter
    got = o.metrics.counter("oracle.ipm_iters").value
    assert got == orc.n_iters_f32 + orc.n_iters_f64
    assert (o.metrics.counter("oracle.ipm_iters_f64").value
            == orc.n_iters_f64)
    # The rate gauges mirror the ledger.
    g = o.metrics.gauge("oracle.wasted_iter_frac").value
    assert abs(g - orc.wasted_iter_frac) < 1e-12
    assert (o.metrics.gauge("oracle.phase2_survivor_frac").value
            == orc.phase2_survivor_frac)


def test_phase1_iters_override_and_validation():
    prob = make("double_integrator", N=3, theta_box=1.5)
    orc = Oracle(prob, backend="cpu", two_phase=True, phase1_iters=25)
    assert orc.point_p1 == 25 and orc.point_p2 == 5
    try:
        Oracle(prob, backend="cpu", two_phase=True, phase1_iters=0)
        raise AssertionError("phase1_iters=0 must be rejected")
    except ValueError:
        pass
    # Degenerate split (phase1 >= schedule) falls back to single phase.
    deg = Oracle(prob, backend="cpu", two_phase=True, phase1_iters=99)
    assert not deg._point_cohort and not deg._simplex_cohort
    # serial forces the knobs off (the conservative baseline contract).
    ser = Oracle(prob, backend="serial", two_phase=True, warm_start=True)
    assert not ser.two_phase and not ser.warm_start


def test_cpu_twin_mirrors_two_phase_knobs():
    prob = make("inverted_pendulum", N=2)
    orc = Oracle(prob, backend="cpu", two_phase=True, phase1_iters=17,
                 warm_start=True)
    twin = orc.cpu_twin(prob)
    assert twin.two_phase and twin.warm_start
    assert twin.phase1_iters == 17
    assert (twin.point_p1, twin.point_p2) == (orc.point_p1, orc.point_p2)


# -- build-level parity + warm starts ----------------------------------------


def test_two_phase_build_tree_identical():
    """The two-phase cohort is a pure work optimization: survivors get
    exactly the remaining schedule, so the partition (regions, nodes,
    leaf deltas, leaf geometry) must be IDENTICAL to the single-phase
    build's, at strictly fewer f64 iterations."""
    prob = make("inverted_pendulum", N=2)
    out = {}
    for tp in (False, True):
        cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                              backend="cpu", batch_simplices=32,
                              max_depth=10, ipm_two_phase=tp,
                              warm_start_tree=False)
        orc = make_oracle(prob, cfg)
        res = build_partition(prob, cfg, oracle=orc)
        leaves = res.tree.converged_leaves()
        out[tp] = ((res.stats["regions"], res.stats["tree_nodes"],
                    res.stats["uncertified"],
                    [res.tree.leaf_data[n].delta_idx for n in leaves],
                    [res.tree.vertices[n].tobytes() for n in leaves]),
                   orc)
    assert out[False][0] == out[True][0]
    orc = out[True][1]
    assert orc.n_iters_f64 < orc.n_iters_f64_fixed
    assert orc.phase2_survivor_frac > 0.0
    assert orc.wasted_iter_frac > 0.0


def test_warm_start_build_accepts_donors_and_stays_sound(rng):
    """Tree warm-starts in a real build: donors flow, the merit gate
    accepts re-centered sibling iterates, and the resulting partition
    keeps the eps-suboptimality guarantee at sampled points."""
    prob = make("inverted_pendulum", N=2)
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=32, max_depth=12,
                          ipm_two_phase=True, warm_start_tree=True)
    orc = make_oracle(prob, cfg)
    assert orc.warm_start
    res = build_partition(prob, cfg, oracle=orc)
    assert orc.n_warm_attempts > 0
    assert orc.warmstart_accept_rate > 0.5
    tree = res.tree
    ref = Oracle(prob, backend="cpu")
    pts = rng.uniform(prob.theta_lb, prob.theta_ub, size=(12, 2))
    sol = ref.solve_vertices(pts)
    from explicit_hybrid_mpc_tpu.partition import geometry
    checked = 0
    for k, th in enumerate(pts):
        n = tree.locate(th, res.roots)
        if n < 0 or tree.leaf_data[n] is None:
            continue
        ld = tree.leaf_data[n]
        if not ld.certified or not np.isfinite(sol.Vstar[k]):
            continue
        lam = geometry.barycentric(tree.vertices[n], th)
        J = lam @ ld.vertex_costs
        assert J <= sol.Vstar[k] + 0.5 + 1e-6
        checked += 1
    assert checked > 0


def test_iteration_ledger_folds_through_device_fallback():
    """A device failure rerouted to the CPU twin must fold the ENTIRE
    statistic set back -- the iteration ledger behind the exact
    ipm_iters / wasted_iter_frac figures, not just solve counts."""
    from explicit_hybrid_mpc_tpu.partition.frontier import FrontierEngine

    prob = make("inverted_pendulum", N=2)

    class Flaky(Oracle):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self._n = 0

        def dispatch_pairs(self, th, ds, warm=None):
            self._n += 1
            if self._n % 2 == 1:
                raise RuntimeError("injected device failure")
            return super().dispatch_pairs(th, ds, warm=warm)

    # Speculation off: its idle-device gate reads the timing-dependent
    # device_frac EMA, so a fallback-slowed run legitimately speculates
    # differently than a clean one -- the ledger exactly counts the
    # work each run ACTUALLY did either way, but cross-run equality
    # (what this test pins) is only defined without speculation.
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=32, max_depth=8,
                          speculate=False)
    flaky = Flaky(prob, backend="cpu", two_phase=True, warm_start=True)
    eng = FrontierEngine(prob, flaky, cfg)
    res = eng.run()
    clean = Oracle(prob, backend="cpu", two_phase=True, warm_start=True)
    res2 = build_partition(prob, cfg, oracle=clean)
    assert eng.n_device_failures > 0
    assert res.stats["regions"] == res2.stats["regions"]
    assert flaky.n_iters_f64 == clean.n_iters_f64
    assert flaky.n_iters_f64_fixed == clean.n_iters_f64_fixed
    assert flaky.n_tp_cells == clean.n_tp_cells


def test_compiled_shapes_warm_covers_build():
    """Shape-guard satellite: a short build must not JIT any padded
    bucket bench.warm_oracle didn't pre-warm -- now including the
    phase-2 cohort buckets."""
    import bench

    prob = make("inverted_pendulum", N=2)
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=16, max_depth=8,
                          max_steps=6)
    orc = make_oracle(prob, cfg)
    assert orc.two_phase and orc.warm_start  # cfg defaults reach oracle
    # Shrink every bucket family so the sweep stays test-sized.
    orc.points_cap = 64
    orc.max_pairs_per_call = 64
    orc.max_simplex_rows_per_call = 64
    bench.warm_oracle(orc, prob)
    warm = set(orc.compiled_shapes)
    assert any(f == "pairs_p2" for f, _ in warm)  # cohort buckets warmed
    assert any(f == "simplex_p2" for f, _ in warm)
    build_partition(prob, cfg, oracle=orc)
    new = orc.compiled_shapes - warm
    assert not new, f"unwarmed shapes JITed mid-build: {sorted(new)}"
