"""Partition engine tests: termination, coverage, and the central property
-- every certified leaf's law is eps-suboptimal and feasible at sampled
interior points (SURVEY.md section 5: "leaf certificate => sampled thetas
satisfy eps-suboptimality vs a reference solver")."""

import os

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        build_partition)
from explicit_hybrid_mpc_tpu.problems.registry import make
from explicit_hybrid_mpc_tpu.utils.logging import RunLog

EPS = 0.5


@pytest.fixture(scope="module")
def di_partition():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=64, max_depth=20)
    res = build_partition(prob, cfg)
    return prob, cfg, res


def test_terminates_all_certified(di_partition):
    prob, cfg, res = di_partition
    assert res.stats["uncertified"] == 0
    assert res.stats["regions"] > 10
    assert res.stats["regions"] == res.tree.n_regions()


def test_coverage_and_disjointness(di_partition, rng):
    prob, cfg, res = di_partition
    tree = res.tree
    leaves = tree.converged_leaves()
    vols = sum(geometry.simplex_volume(tree.vertices[n]) for n in leaves)
    box_vol = float(np.prod(prob.theta_ub - prob.theta_lb))
    assert np.isclose(vols, box_vol, rtol=1e-9)
    # Interior sample points: located leaf contains them.
    for _ in range(30):
        th = rng.uniform(prob.theta_lb, prob.theta_ub)
        n = tree.locate(th, res.roots)
        assert n >= 0 and tree.leaf_data[n] is not None
        assert geometry.contains(tree.vertices[n], th, tol=1e-9)


def test_eps_suboptimality_property(di_partition, rng):
    """The certified guarantee: the interpolated full input sequence is
    feasible and its cost is within eps_a of V*(theta)."""
    prob, cfg, res = di_partition
    tree = res.tree
    can = prob.canonical
    oracle = Oracle(prob, backend="cpu")
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(40, 2))
    sol = oracle.solve_vertices(thetas)
    for k, th in enumerate(thetas):
        n = tree.locate(th, res.roots)
        ld = tree.leaf_data[n]
        d = max(ld.delta_idx, 0)
        lam = geometry.barycentric(tree.vertices[n], th)
        zbar = lam @ ld.vertex_z
        # Feasibility of the interpolated sequence.
        viol = np.max(can.G[d] @ zbar - can.w[d] - can.S[d] @ th)
        assert viol <= 1e-6, f"theta {th}: violation {viol}"
        # eps-suboptimality vs the enumerated optimum.
        J = can.value(d, th, zbar)
        assert J <= sol.Vstar[k] + EPS + 1e-6, (
            f"theta {th}: J={J} V*={sol.Vstar[k]}")


def test_vertex_cache_shares_work_and_bounds_memory():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=64, max_depth=20)
    oracle = Oracle(prob, backend="cpu")
    eng = FrontierEngine(prob, oracle, cfg)
    res = eng.run()
    # Far fewer unique vertex solves than (p+1) per processed simplex
    # (bisection shares vertices; the cache must capture that even though
    # rows are evicted once no open simplex references them).
    processed = res.stats["tree_nodes"]
    assert res.stats["unique_vertex_solves"] < 0.8 * processed * 3
    # Eviction: with the frontier drained every row is released.
    assert len(eng.cache) == 0
    assert eng._refcount == {}
    # The high-water mark is bounded by live-frontier vertices, far below
    # the total unique vertices ever solved.
    assert 0 < res.stats["cache_peak_vertices"] <= res.stats[
        "unique_vertex_solves"]
    assert res.stats["cache_peak_mb"] >= 0


def test_checkpoint_resume(tmp_path):
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=16, max_depth=20)
    oracle = Oracle(prob, backend="cpu")
    eng = FrontierEngine(prob, oracle, cfg)
    for _ in range(3):
        eng.step()
    ckpt = os.path.join(tmp_path, "snap.pkl")
    eng.save_checkpoint(ckpt)
    # Finish the original.
    res_full = eng.run()
    # Resume from snapshot and finish independently.
    eng2 = FrontierEngine.resume(ckpt, prob, Oracle(prob, backend="cpu"))
    res_resumed = eng2.run()
    assert res_resumed.stats["regions"] == res_full.stats["regions"]
    assert res_resumed.tree.max_depth() == res_full.tree.max_depth()


def test_device_failure_falls_back_to_cpu():
    """Injected device failures must not abort the build: every failed
    batch retries on the CPU fallback oracle and the result matches a
    clean build exactly (same kernel, deterministic -- SURVEY.md 6.3,
    round-1 verdict item 8)."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=32, max_depth=20)
    clean = build_partition(prob, cfg, Oracle(prob, backend="cpu"))

    class FlakyOracle(Oracle):
        """Raises on every other solve_vertices / simplex call."""

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self._calls = 0

        def _maybe_fail(self):
            self._calls += 1
            if self._calls % 2 == 1:
                raise RuntimeError("injected device failure")

        def dispatch_vertices(self, thetas):
            # The engine issues point solves via dispatch/wait (build
            # pipeline); failing the dispatch exercises the "failed"
            # handle marker -> CPU fallback path in BuildPipeline.
            self._maybe_fail()
            return super().dispatch_vertices(thetas)

        def dispatch_pairs(self, thetas, ds):
            self._maybe_fail()
            return super().dispatch_pairs(thetas, ds)

        def solve_simplex_min(self, Ms, ds):
            self._maybe_fail()
            return super().solve_simplex_min(Ms, ds)

        def simplex_feasibility(self, Ms, ds):
            self._maybe_fail()
            return super().simplex_feasibility(Ms, ds)

    eng = FrontierEngine(prob, FlakyOracle(prob, backend="cpu"), cfg)
    res = eng.run()
    assert eng.n_device_failures > 0
    assert res.stats["device_failures"] == eng.n_device_failures
    assert res.stats["regions"] == clean.stats["regions"]
    assert res.stats["tree_nodes"] == clean.stats["tree_nodes"]
    assert not res.stats["truncated"]


def test_time_budget_truncates_honestly():
    """A zero wall-clock budget must stop before the first step and report
    truncated=True with the frontier intact (the benchmark capture's
    guarantee that slow platforms still produce a number)."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=64,
                          time_budget_s=0.0)
    res = build_partition(prob, cfg, Oracle(prob, backend="cpu"))
    assert res.stats["truncated"]
    assert res.stats["steps"] == 0
    assert res.stats["frontier_left"] > 0


def test_inherited_bounds_parity_and_savings():
    """Round-2 verdict item 2: inheriting per-delta Farkas exclusions and
    simplex-min lower bounds down the tree must (a) produce a tree at
    least as tight as an inheritance-free build -- CERTIFIED decisions
    match (round-B exact re-solve), while an inherited +inf exclusion is
    STRICTLY MORE ACCURATE than re-solving (a child phase-1 that stalls
    demotes an exactly-known infeasible to 'split'), so the uninherited
    build may subdivide infeasible space slightly further -- and (b)
    actually cut stage-2 joint-QP volume on a hybrid problem.  Both
    partitions are sound; soundness is what the volume check asserts."""
    from explicit_hybrid_mpc_tpu.post import analysis

    prob = make("inverted_pendulum", N=3)
    stats = {}
    vol = {}
    for inherit in (False, True):
        cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                              backend="cpu", batch_simplices=64,
                              max_depth=14, inherit_bounds=inherit)
        res = build_partition(prob, cfg, Oracle(prob, backend="cpu"))
        stats[inherit] = res.stats
        vol[inherit] = analysis.partition_report(
            res.tree, res.roots)["volume_certified_frac"]
    # Inheritance never certifies LESS; any count gap is the infeasible-
    # closure asymmetry above and stays tiny.
    assert stats[True]["regions"] <= stats[False]["regions"]
    assert (stats[False]["regions"] - stats[True]["regions"]
            <= max(4, stats[False]["regions"] // 100))
    assert abs(vol[True] - vol[False]) < 1e-9
    assert stats[True]["max_depth"] == stats[False]["max_depth"]
    assert stats[True]["uncertified"] == stats[False]["uncertified"]
    # The point of the feature: measurably fewer joint simplex QPs.
    assert stats[True]["inherited_skips"] > 0
    assert stats[True]["simplex_solves"] < stats[False]["simplex_solves"]
    # Point-solve volume only shrinks (the uninherited build's extra
    # infeasible-space splits mint extra vertices).
    assert stats[True]["point_solves"] <= stats[False]["point_solves"]


def test_serial_vs_batched_region_parity():
    """North-star requirement: identical region count between the serial
    oracle baseline and the batched backend (BASELINE.json)."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    counts = {}
    for backend in ("serial", "cpu"):
        cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                              backend=backend, batch_simplices=32,
                              max_depth=20)
        res = build_partition(prob, cfg, Oracle(prob, backend=backend))
        counts[backend] = (res.stats["regions"], res.stats["tree_nodes"])
    assert counts["serial"] == counts["cpu"]


def test_masked_point_solves_tree_parity_and_savings():
    """cfg.mask_point_solves skips point QPs for commutations
    Farkas-excluded on an ancestor.  A skipped cell is fabricated as
    (V=+inf, conv=False) -- exactly what the solver returns for an
    infeasible QP -- so the build must be TREE-IDENTICAL to the unmasked
    one while issuing measurably fewer point solves."""
    prob = make("inverted_pendulum", N=3)
    out = {}
    for masked in (False, True):
        cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                              backend="cpu", batch_simplices=64,
                              max_depth=14, mask_point_solves=masked)
        res = build_partition(prob, cfg, Oracle(prob, backend="cpu"))
        leaves = res.tree.converged_leaves()
        out[masked] = (res.stats, leaves,
                       [res.tree.leaf_data[n].delta_idx for n in leaves],
                       [res.tree.vertices[n] for n in leaves])
    sa, sb = out[False][0], out[True][0]
    assert sa["regions"] == sb["regions"]
    assert sa["tree_nodes"] == sb["tree_nodes"]
    assert out[False][2] == out[True][2]
    for Va, Vb in zip(out[False][3], out[True][3]):
        np.testing.assert_array_equal(Va, Vb)
    # The point of the feature: skipped point QPs, identical everything.
    assert sb["masked_point_skips"] > 0
    assert sb["point_solves"] < sa["point_solves"]
    assert sa["masked_point_skips"] == 0


def test_prefetch_parity():
    """The build pipeline (cfg.prefetch_solves / pipeline_depth) must be
    invisible in the TREE: identical partition vs the strictly-
    synchronous loop.  Since the pipelined executor re-plans
    authoritatively at commit time and the dedup window coalesces
    duplicate in-flight requests, the solve count is EXACTLY the
    synchronous build's (the old single-slot prefetch re-solved
    midpoints shared across batch boundaries; the window removes
    those).  Speculation is off here -- it trades extra solves for
    latency by design and has its own parity test
    (tests/test_pipeline.py)."""
    prob = make("inverted_pendulum", N=3)
    out = {}
    for pf in (False, True):
        cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                              backend="cpu", batch_simplices=64,
                              max_depth=14, prefetch_solves=pf,
                              speculate=False)
        res = build_partition(prob, cfg, Oracle(prob, backend="cpu"))
        leaves = res.tree.converged_leaves()
        out[pf] = (res.stats,
                   (res.stats["regions"], res.stats["tree_nodes"],
                    [res.tree.leaf_data[n].delta_idx for n in leaves],
                    [res.tree.vertices[n].tobytes() for n in leaves]))
    assert out[False][1] == out[True][1]          # tree identity
    sa, sb = out[False][0], out[True][0]
    assert sb["prefetched_steps"] > 0             # it actually pipelined
    assert sa["prefetched_steps"] == 0
    assert sb["pipeline_fill_frac"] > 0
    # Stage-2 work is unaffected; the dedup window makes the pipelined
    # point-solve count exactly the synchronous build's.
    assert sb["simplex_solves"] == sa["simplex_solves"]
    assert sb["point_solves"] == sa["point_solves"]


def test_batched_stage1_matches_scalar():
    """certify_stage1_batch must reproduce the scalar
    certify_suboptimal_stage1 decision (status, delta, gap, pending set,
    partial gaps) for every node of real frontier batches."""
    from explicit_hybrid_mpc_tpu.partition import certify

    prob = make("inverted_pendulum", N=3)
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_depth=10,
                          mask_point_solves=False, inherit_bounds=False)
    oracle = Oracle(prob, backend="cpu")
    eng = FrontierEngine(prob, oracle, cfg)
    checked = 0
    for _ in range(6):
        if not eng.frontier:
            break
        nodes = list(eng.frontier)[:64]
        plan = eng._plan_missing(nodes)
        eng._merge_plan_results(plan, *eng._pipe.serve(plan))
        sds, (bverts, bV, bconv, bgrad, _bu0, _bz, bVstar, bdstar) = \
            eng._gather_batch(nodes)
        batch = certify.certify_stage1_batch(
            bverts, bV, bconv, bgrad, bVstar, bdstar,
            cfg.eps_a, cfg.eps_r)
        for n, rb in zip(nodes, batch):
            rs = certify.certify_suboptimal_stage1(sds[n], cfg.eps_a,
                                                   cfg.eps_r)
            assert rb.status == rs.status, (rb.status, rs.status)
            checked += 1
            if rs.status == "certified":
                assert rb.delta_idx == rs.delta_idx
                assert np.isclose(rb.gap, rs.gap)
            elif rs.status == "pending":
                np.testing.assert_array_equal(rb.pending_deltas,
                                              rs.pending_deltas)
                np.testing.assert_array_equal(rb._candidates,
                                              rs._candidates)
                np.testing.assert_allclose(rb._stage1_gap, rs._stage1_gap,
                                           equal_nan=True)
            elif rs.status == "split" and np.isfinite(rs.gap):
                assert np.isclose(rb.gap, rs.gap)
        eng.step()
    assert checked > 150  # the comparison saw a real mix of batches
