"""CLI surface: flag parsing, build outputs, resume path, error cases."""

import json
import os

import pytest

from explicit_hybrid_mpc_tpu.main import build_parser, main


def test_list(capsys):
    assert main(["--list", "-e", "x"]) == 0
    out = capsys.readouterr().out
    assert "double_integrator" in out and "quadrotor" in out


def test_build_and_outputs(tmp_path):
    prefix = str(tmp_path / "out" / "di")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "64", "-o", prefix,
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5",
               "--simulate", "10"])
    assert rc == 0
    assert os.path.exists(f"{prefix}.tree.pkl")
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["regions"] > 0 and not stats["truncated"]
    assert os.path.exists(f"{prefix}.log.jsonl")
    sim = json.load(open(f"{prefix}.sim.json"))
    assert sim["cost_ratio"] < 1.1


def test_feasible_variant(tmp_path):
    prefix = str(tmp_path / "feas")
    rc = main(["-e", "double_integrator", "--algorithm", "feasible",
               "--backend", "cpu", "-o", prefix,
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["regions"] > 0


def test_bad_example():
    with pytest.raises(KeyError):
        main(["-e", "not_a_problem", "-a", "0.1", "--backend", "cpu"])


def test_parser_rejects_bad_algorithm():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["-e", "x", "--algorithm", "bogus"])


def test_bad_problem_arg():
    with pytest.raises(SystemExit):
        main(["-e", "double_integrator", "-a", "0.1", "--backend", "cpu",
              "--problem-arg", "oops"])
