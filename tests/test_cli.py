"""CLI surface: flag parsing, build outputs, resume path, error cases."""

import json
import os

import pytest

from explicit_hybrid_mpc_tpu.main import build_parser, main


def test_list(capsys):
    assert main(["--list", "-e", "x"]) == 0
    out = capsys.readouterr().out
    assert "double_integrator" in out and "quadrotor" in out


def test_build_and_outputs(tmp_path):
    prefix = str(tmp_path / "out" / "di")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "64", "-o", prefix,
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5",
               "--simulate", "10"])
    assert rc == 0
    assert os.path.exists(f"{prefix}.tree.pkl")
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["regions"] > 0 and not stats["truncated"]
    assert os.path.exists(f"{prefix}.log.jsonl")
    sim = json.load(open(f"{prefix}.sim.json"))
    assert sim["cost_ratio"] < 1.1
    # The artifact carries full trajectories and renders the paper-style
    # closed-loop figure on its own.
    assert len(sim["trajectories"]["explicit"]["states"]) == 11
    from explicit_hybrid_mpc_tpu.post import figures  # forces Agg
    fig_path = str(tmp_path / "cl_from_json.png")
    figures.plot_closed_loop(sim, save=fig_path)
    assert os.path.getsize(fig_path) > 0
    import matplotlib.pyplot as plt
    plt.close("all")


def test_feasible_variant(tmp_path):
    prefix = str(tmp_path / "feas")
    rc = main(["-e", "double_integrator", "--algorithm", "feasible",
               "--backend", "cpu", "-o", prefix, "--simulate", "8",
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["regions"] > 0
    # --simulate on a feasible-variant build must go through the
    # semi-explicit controller (leaf delta + online QP) and stay sane.
    sim = json.load(open(f"{prefix}.sim.json"))
    assert sim["cost_ratio"] < 1.5


def test_profile_flag_writes_trace_and_utilization(tmp_path):
    """--profile writes a jax.profiler trace dir; the JSONL metrics carry
    the device-utilization proxy (SURVEY.md section 6.1/6.5)."""
    prefix = str(tmp_path / "pr")
    trace = str(tmp_path / "trace")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "32", "-o", prefix, "--profile", trace,
               "--profile-steps", "2",
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    files = [f for _, _, fs in os.walk(trace) for f in fs]
    assert files, "profiler trace directory is empty"
    lines = [json.loads(ln) for ln in open(f"{prefix}.log.jsonl")]
    steps = [ln for ln in lines if "device_frac" in ln]
    assert steps
    assert all(0.0 <= ln["device_frac"] <= 1.01 for ln in steps)
    assert all(ln["oracle_s"] <= ln["step_s"] + 1e-6 for ln in steps)


def test_resume_uses_snapshot_cfg(tmp_path, capsys):
    """A resumed build must take its solver flags from the snapshot, and
    say so when the CLI disagrees (ADVICE round 1: CLI --precision could
    silently switch solver precision mid-build)."""
    prefix = str(tmp_path / "ck")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "32", "-o", prefix, "--checkpoint-every", "1",
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    ckpt = f"{prefix}.ckpt.pkl"
    assert os.path.exists(ckpt)
    prefix2 = str(tmp_path / "ck2")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--precision", "mixed", "--batch", "64", "-o", prefix2,
               "--resume", ckpt,
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "resume: using snapshot precision='f64'" in err
    assert "resume: using snapshot batch_simplices=32" in err
    # Output paths belong to the NEW run: the resumed build writes its own
    # log/stats under -o prefix2 and leaves the old run's log untouched.
    assert os.path.exists(f"{prefix2}.log.jsonl")
    assert os.path.exists(f"{prefix2}.stats.json")
    old_log_size = os.path.getsize(f"{prefix}.log.jsonl")
    assert old_log_size > 0  # written only by the first run


def test_resume_extends_truncated_build(tmp_path):
    """max_steps is a RUN-BUDGET flag: resuming a max_steps-truncated
    build with a larger --max-steps must finish it, and the problem
    constructor args must come from the snapshot (passing different
    --problem-arg values used to corrupt the restored solve cache --
    found by e2e verify, round 3)."""
    prefix = str(tmp_path / "tr")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "64", "-o", prefix, "--checkpoint-every", "2",
               "--max-steps", "6",
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["truncated"]
    prefix2 = str(tmp_path / "tr2")
    # No --problem-arg, no --backend: both must come from the snapshot.
    rc = main(["-e", "double_integrator", "--resume", f"{prefix}.ckpt.pkl",
               "-o", prefix2, "--max-steps", "500"])
    assert rc == 0
    stats2 = json.load(open(f"{prefix2}.stats.json"))
    assert not stats2["truncated"] and stats2["regions"] > 0


def test_bad_example():
    with pytest.raises(KeyError):
        main(["-e", "not_a_problem", "-a", "0.1", "--backend", "cpu"])


def test_parser_rejects_bad_algorithm():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["-e", "x", "--algorithm", "bogus"])


def test_bad_problem_arg():
    with pytest.raises(SystemExit):
        main(["-e", "double_integrator", "-a", "0.1", "--backend", "cpu",
              "--problem-arg", "oops"])


def test_prune_rows_flag_takes_effect(tmp_path, monkeypatch):
    """ADVICE r4 (medium): --prune-rows was a silent no-op -- main()
    built a plain Oracle and never reached build_partition's PrunedOracle
    branch.  The CLI must construct PrunedOracle, and must error out when
    the flag cannot take effect (serial / mesh backends)."""
    from explicit_hybrid_mpc_tpu.oracle import prune as prune_mod

    made = []
    real = prune_mod.PrunedOracle

    class Spy(real):
        def __init__(self, *a, **kw):
            made.append(1)
            super().__init__(*a, **kw)

    monkeypatch.setattr(prune_mod, "PrunedOracle", Spy)
    prefix = str(tmp_path / "pr")
    rc = main(["-e", "double_integrator", "-a", "0.2", "--backend", "cpu",
               "--batch", "32", "-o", prefix, "--prune-rows",
               "--problem-arg", "N=3", "--problem-arg", "theta_box=1.5"])
    assert rc == 0 and made, "--prune-rows did not construct PrunedOracle"
    with pytest.raises(SystemExit, match="prune-rows"):
        main(["-e", "double_integrator", "--backend", "serial",
              "--prune-rows", "-o", str(tmp_path / "x"),
              "--problem-arg", "N=3"])


def test_hybrid_simulate_routes_boundary_leaves(tmp_path, monkeypatch):
    """ADVICE r4 (medium): --simulate on a hybrid --boundary-depth build
    deployed the pure ExplicitController, interpolating boundary leaves'
    fabricated payloads.  main() must hand the semi-explicit mask to the
    simulator so exactly those leaves take the online fixed-delta QP."""
    from explicit_hybrid_mpc_tpu.sim import simulator as sim_mod

    seen = {}
    real = sim_mod.SemiExplicitController

    class Spy(real):
        def __init__(self, *a, **kw):
            seen["semi_mask"] = kw.get("semi_mask")
            super().__init__(*a, **kw)

    monkeypatch.setattr(sim_mod, "SemiExplicitController", Spy)
    prefix = str(tmp_path / "hy")
    rc = main(["-e", "mass_spring", "-a", "1.0", "-r", "0.5",
               "--backend", "cpu", "--batch", "128", "--max-depth", "12",
               "--boundary-depth", "8", "-o", prefix, "--simulate", "5",
               "--problem-arg", "N=4", "--problem-arg", "theta_box=3.0"])
    assert rc == 0
    stats = json.load(open(f"{prefix}.stats.json"))
    assert stats["semi_explicit"] > 0, "build produced no boundary leaves"
    mask = seen.get("semi_mask")
    assert mask is not None and mask.any(), (
        "simulate did not deploy SemiExplicitController with the "
        "boundary-leaf mask")
    assert not mask.all()  # hybrid: certified interior stays explicit
