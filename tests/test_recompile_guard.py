"""Runtime recompile sentinel (analysis/recompile_guard.py, ISSUE 6).

The acceptance case: the guard catches a deliberately shape-unstable
jit call.  Plus: ledger-based (oracle.compiled_shapes) detection, warn
mode's health.recompile event into the obs stream, the HealthMonitor
adopting external health events, the frontier's steady-state wiring,
and a healthy end-to-end build emitting ZERO recompile events.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.analysis.recompile_guard import (
    RecompileError, RecompileGuard)
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


class _LedgerOracle:
    """Duck-typed stand-in for Oracle's compiled-shape ledger."""

    def __init__(self):
        self.compiled_shapes = {("grid", 8), ("pairs", 16)}


def test_guard_catches_shape_unstable_jit_call():
    fn = jax.jit(lambda x: x * 2.0)
    with pytest.raises(RecompileError, match="jit-cache"):
        with RecompileGuard(watch=[fn], action="raise"):
            fn(jnp.zeros(4))   # first lowering INSIDE the guarded phase
            fn(jnp.zeros(16))  # second shape: the violation


def test_guard_passes_shape_stable_jit_call():
    fn = jax.jit(lambda x: x * 2.0)
    fn(jnp.zeros(4))  # compile before the guarded phase
    with RecompileGuard(watch=[fn], action="raise"):
        for _ in range(3):
            fn(jnp.ones(4))  # same shape: cache hits only


def test_guard_ledger_warn_mode_emits_and_rearms():
    o = _LedgerOracle()
    g = RecompileGuard(oracle=o, action="warn", label="t")
    assert g.check() is None
    o.compiled_shapes.add(("grid", 32))
    ev = g.check(step=7)
    assert ev["name"] == "health.recompile" and ev["severity"] == "warn"
    assert ev["step"] == 7 and "grid[32]" in ev["msg"]
    # Re-armed: the same ledger state does not re-fire.
    assert g.check() is None
    assert g.n_violations == 1


def test_guard_event_lands_in_obs_stream(tmp_path):
    path = str(tmp_path / "s.obs.jsonl")
    with obs_lib.Obs("jsonl", path=path) as o:
        lo = _LedgerOracle()
        g = RecompileGuard(oracle=lo, obs=o, action="warn")
        lo.compiled_shapes.add(("grid", 64))
        g.check()
    recs = obs_lib.load_jsonl(path)
    evs = [r for r in recs if r.get("name") == "health.recompile"]
    assert len(evs) == 1 and evs[0]["severity"] == "warn"


def test_guard_exit_never_masks_inflight_exception():
    fn = jax.jit(lambda x: x * 2.0)
    with pytest.raises(KeyError):
        with RecompileGuard(watch=[fn], action="raise"):
            fn(jnp.zeros(4))
            fn(jnp.zeros(8))  # would raise at exit...
            raise KeyError("boom")  # ...but the real error wins


def test_guard_rejects_unusable_probes():
    with pytest.raises(ValueError, match="oracle"):
        RecompileGuard()
    with pytest.raises(ValueError, match="compiled_shapes"):
        RecompileGuard(oracle=object())
    with pytest.raises(ValueError, match="_cache_size"):
        RecompileGuard(watch=[lambda x: x])


def test_health_monitor_adopts_external_health_events():
    mon = HealthMonitor()
    evs = mon.feed({"kind": "event", "name": "health.recompile",
                    "severity": "warn", "value": 1, "msg": "new shape"})
    assert mon.worst == "warn" and mon.exit_code == 1
    assert evs and evs[0]["name"] == "health.recompile"
    assert any(e["name"] == "health.recompile" for e in mon.events)
    mon.feed({"kind": "event", "name": "health.stall",
              "severity": "critical", "msg": "frozen"})
    assert mon.worst == "critical" and mon.exit_code == 2


def test_config_validates_guard_mode():
    with pytest.raises(ValueError, match="recompile_guard"):
        PartitionConfig(eps_a=0.2, recompile_guard="loud")
    cfg = PartitionConfig(eps_a=0.2, recompile_guard="warn")
    assert cfg.recompile_guard == "warn"


def test_frontier_guard_fires_on_synthetic_ledger_growth(tmp_path):
    """End-to-end wiring: a small build with the guard in warn mode is
    CLEAN, and a synthetic post-warmup ledger insertion produces the
    health.recompile event via the engine's own hook."""
    from explicit_hybrid_mpc_tpu.partition.frontier import FrontierEngine

    prob = make("double_integrator", N=3, theta_box=1.5)
    path = str(tmp_path / "b.obs.jsonl")
    cfg = PartitionConfig(eps_a=0.2, backend="cpu", batch_simplices=16,
                          obs="jsonl", obs_path=path,
                          recompile_guard="warn")
    from explicit_hybrid_mpc_tpu.partition.frontier import make_oracle

    with obs_lib.Obs("jsonl", path=path) as o:
        oracle = make_oracle(prob, cfg)
        eng = FrontierEngine(prob, oracle, cfg, obs=o)
        while eng.frontier and eng.steps < 200:
            eng.step()
        assert eng.tree.n_regions() > 100
        assert eng._rc_guard is not None
        # The build itself must be recompile-clean...
        assert eng._rc_guard.n_violations == 0
        # ...and a shape minted after warmup is caught by the same hook
        # the step loop calls (forced full-batch path).
        eng._rc_steady_steps = eng._GUARD_WARMUP_FULL_STEPS + 1
        eng.oracle.compiled_shapes.add(("synthetic", 12345))
        eng._guard_step(cfg.batch_simplices)
        assert eng._rc_guard.n_violations == 1
    recs = obs_lib.load_jsonl(path)
    evs = [r for r in recs if r.get("name") == "health.recompile"]
    assert len(evs) == 1 and "synthetic" in evs[0]["msg"]


def test_frontier_guard_absolves_partial_batch_shapes():
    """A backlog dip's partial wave legitimately mints a small bucket;
    the next FULL-size step must not inherit it as a violation (the
    partial branch re-arms an armed guard)."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.2, backend="cpu", batch_simplices=16,
                          recompile_guard="raise")
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    eng._rc_steady_steps = eng._GUARD_WARMUP_FULL_STEPS + 1
    eng.oracle.compiled_shapes.add(("partial_wave", 4))
    eng._guard_step(cfg.batch_simplices - 1)  # partial: exempt + re-arm
    eng._guard_step(cfg.batch_simplices)      # full: must NOT raise
    assert eng._rc_guard.n_violations == 0
    # A FULL step's own mint is still caught by its own end-of-step
    # check, partial re-arms notwithstanding.
    eng.oracle.compiled_shapes.add(("full_wave", 8))
    with pytest.raises(RecompileError):
        eng._guard_step(cfg.batch_simplices)


def test_frontier_guard_raise_mode_aborts():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.2, backend="cpu", batch_simplices=16,
                          recompile_guard="raise")
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    eng._rc_steady_steps = eng._GUARD_WARMUP_FULL_STEPS + 1
    eng.oracle.compiled_shapes.add(("synthetic", 999))
    with pytest.raises(RecompileError):
        eng._guard_step(cfg.batch_simplices)


def test_healthy_build_with_guard_emits_no_events(tmp_path):
    path = str(tmp_path / "clean.obs.jsonl")
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.2, backend="cpu", batch_simplices=32,
                          obs="jsonl", obs_path=path,
                          recompile_guard="warn")
    res = build_partition(prob, cfg)
    assert res.stats["uncertified"] == 0
    recs = obs_lib.load_jsonl(path)
    assert not [r for r in recs
                if str(r.get("name", "")).startswith("health.")]
