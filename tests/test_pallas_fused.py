"""Fused arena kernel (descent -> barycentric eval -> certified-box
clamp in ONE pallas_call, online/pallas_eval.arena_eval_fused) vs its
references, in interpret mode on CPU (on TPU the same kernel compiles
via Mosaic).

Parity contract (docs/serving.md "Device-resident arena"):

- vs the f64 host evaluator (online/evaluator.py): EXACT leaf ids on
  well-separated queries (disjoint cells queried at their centroids)
  and u/cost to f32 tolerance.  Point location runs the argmax in f32
  on the kernel path, so knife-edge queries equidistant between two
  leaves may legitimately tie-break differently from the f64
  reference -- the suite queries centroids precisely to stay off that
  edge (same caveat as test_pallas_eval.py).
- vs the plain-XLA twin (arena_eval_xla) over the SAME buffers: exact
  leaf/served/clamped agreement, values to 1e-5.  Values are NOT
  asserted bitwise ACROSS backends (different f32 reduction order);
  each backend is deterministic WITHIN itself, which is what the
  serve_bench torn-read audit relies on.
- clamp semantics: a clamped row is bitwise the same backend's
  evaluation of the pre-clipped query.
"""

import numpy as np
import pytest

import explicit_hybrid_mpc_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from explicit_hybrid_mpc_tpu.online import evaluator, export, pallas_eval
from explicit_hybrid_mpc_tpu.serve.arena import DeviceArena


def _synthetic_table(rng, L=40, p=2, n_u=2):
    """Disjoint unit-grid simplices (same construction as
    test_pallas_eval._synthetic_table, replicated so the suites stay
    independently runnable): each simplex uniquely contains its own
    centroid, so location is exact and f32 must agree with f64 on
    ids."""
    from explicit_hybrid_mpc_tpu.partition import geometry

    base = np.vstack([np.zeros(p), np.eye(p)])
    side = int(np.ceil(np.sqrt(L)))
    bary, U, V = [], [], []
    for i in range(L):
        off = np.array([i % side, i // side], dtype=float)[:p]
        verts = 0.8 * base + off + 0.1 * rng.uniform(size=p)
        bary.append(geometry.barycentric_matrix(verts))
        U.append(rng.normal(size=(p + 1, n_u)))
        V.append(np.abs(rng.normal(size=p + 1)))
    return export.LeafTable(
        bary_M=np.stack(bary), U=np.stack(U), V=np.stack(V),
        delta=np.zeros(L, dtype=np.int64),
        node_id=np.arange(L, dtype=np.int64))


def _centroids(table):
    return np.stack([np.linalg.inv(table.bary_M[i])[:-1, :].mean(axis=1)
                     for i in range(table.n_leaves)])


_BOX = (np.zeros(2), np.full(2, 8.0))  # covers the 7x7 grid + margin


@pytest.fixture(scope="module")
def arena_pair():
    """One arena, two tenants at distinct extents, + f64 references."""
    rng = np.random.default_rng(77)
    ta = _synthetic_table(rng, L=40)
    tb = _synthetic_table(rng, L=37)
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, backend="xla")
    arena.publish("a", "v1", ta, *_BOX)
    arena.publish("b", "v1", tb, *_BOX)
    return arena, {"a": ta, "b": tb}


def test_fused_single_controller_vs_f64_evaluator(arena_pair):
    """Interpret-mode fused kernel vs the f64 host evaluator on one
    tenant's centroids: exact leaf ids, all served, nothing clamped,
    u/cost to f32 tolerance."""
    arena, tables = arena_pair
    ta = tables["a"]
    cents = _centroids(ta)
    ref = evaluator.evaluate(evaluator.stage(ta), jnp.asarray(cents))
    out = arena.evaluate("a", cents, backend="pallas")
    assert np.array_equal(out.leaf, np.asarray(ref.leaf))
    assert bool(np.all(out.served))
    assert not bool(np.any(out.clamped))
    np.testing.assert_allclose(out.u[:, :2], np.asarray(ref.u),
                               atol=1e-5)
    np.testing.assert_allclose(out.cost, np.asarray(ref.cost),
                               rtol=1e-5, atol=1e-5)
    assert np.all(out.u[:, 2:] == 0.0)  # padded lanes stay exact zeros


def test_fused_mixed_tenant_parity(arena_pair):
    """Interleaved rows routed to different extents in ONE launch must
    each match their own controller's f64 reference -- the launch-fusion
    tentpole is only a win if routing is exact."""
    arena, tables = arena_pair
    ca, cb = _centroids(tables["a"]), _centroids(tables["b"])
    n = min(len(ca), len(cb))
    names, rows = [], []
    for i in range(n):  # a, b, a, b, ... interleaved
        names += ["a", "b"]
        rows += [ca[i], cb[i]]
    thetas = np.stack(rows)
    for backend in ("xla", "pallas"):
        out = arena.evaluate(names, thetas, backend=backend)
        for key, tab, cents in (("a", tables["a"], ca),
                                ("b", tables["b"], cb)):
            sel = np.asarray([nm == key for nm in names])
            ref = evaluator.evaluate(evaluator.stage(tab),
                                     jnp.asarray(thetas[sel]))
            assert np.array_equal(out.leaf[sel], np.asarray(ref.leaf)), \
                (backend, key)
            np.testing.assert_allclose(out.u[sel, :2],
                                       np.asarray(ref.u), atol=1e-5)
            np.testing.assert_allclose(out.cost[sel],
                                       np.asarray(ref.cost),
                                       rtol=1e-5, atol=1e-5)
        assert bool(np.all(out.served)), backend
        assert out.versions == {"a": "v1", "b": "v1"}


def test_fused_vs_xla_same_buffers(arena_pair):
    """The pallas and XLA backends read the SAME resident buffers and
    must agree exactly on every discrete output (leaf, served, clamped)
    and to 1e-5 on values.  Bitwise value equality is only guaranteed
    WITHIN a backend (module docstring)."""
    arena, tables = arena_pair
    rng = np.random.default_rng(3)
    thetas = rng.uniform(0.0, 7.0, size=(24, 2))
    names = ["a" if i % 3 else "b" for i in range(24)]
    xla = arena.evaluate(names, thetas, backend="xla")
    pal = arena.evaluate(names, thetas, backend="pallas")
    assert np.array_equal(xla.leaf, pal.leaf)
    assert np.array_equal(xla.served, pal.served)
    assert np.array_equal(xla.clamped, pal.clamped)
    np.testing.assert_allclose(xla.u, pal.u, atol=1e-5)
    np.testing.assert_allclose(xla.cost, pal.cost, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_fused_clamp_is_clipped_eval(arena_pair, backend):
    """Out-of-box rows: the kernel must flag them clamped AND return
    bitwise the same backend's evaluation of the pre-clipped query --
    the in-kernel clip is semantically clip-then-evaluate, fused."""
    arena, tables = arena_pair
    lb, ub = _BOX
    rng = np.random.default_rng(5)
    inside = rng.uniform(1.0, 6.0, size=(4, 2))
    outside = np.stack([ub + np.array([1.0, 2.5]),
                        lb - np.array([0.5, 3.0]),
                        np.array([-1.0, 4.0]),
                        np.array([3.0, 9.5])])
    thetas = np.concatenate([inside, outside])
    names = ["a"] * 8
    out = arena.evaluate(names, thetas, backend=backend)
    assert not np.any(out.clamped[:4])
    assert np.all(out.clamped[4:])
    ref = arena.evaluate(names, np.clip(thetas, lb, ub),
                         backend=backend)
    assert not np.any(ref.clamped)
    # Bitwise: same backend, same buffers, same effective query.
    assert np.array_equal(out.u, ref.u)
    assert np.array_equal(out.cost, ref.cost)
    assert np.array_equal(out.leaf, ref.leaf)
    assert np.array_equal(out.served, ref.served)


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_fused_clamp_off_identity(arena_pair, backend):
    """clamp=False (FallbackPolicy mode 'off'): the row boxes widen to
    +-inf so the in-kernel clip is the identity and nothing is flagged,
    even for far-out-of-box queries."""
    arena, _ = arena_pair
    thetas = np.array([[3.3, 3.3], [40.0, -10.0]])
    out = arena.evaluate(["a", "a"], thetas, clamp=False,
                         backend=backend)
    assert not np.any(out.clamped)
    # The far-out row evaluates the RAW point: every lam is way
    # negative, so it must come back unserved rather than clamped.
    assert bool(out.served[0]) and not bool(out.served[1])


def test_fused_within_backend_determinism(arena_pair):
    """Same backend + same buffers + same query => bitwise-identical
    results across repeated launches and batch compositions that keep
    the row (torn-read audits in serve_bench rely on this)."""
    arena, tables = arena_pair
    cents = _centroids(tables["b"])[:8]
    a = arena.evaluate("b", cents, backend="xla")
    b = arena.evaluate("b", cents, backend="xla")
    assert np.array_equal(a.u, b.u) and np.array_equal(a.cost, b.cost)
    # Same rows embedded in a larger mixed batch: row-wise identical.
    mixed_names = ["b"] * 8 + ["a"] * 8
    mixed = np.concatenate([cents, _centroids(tables["a"])[:8]])
    c = arena.evaluate(mixed_names, mixed, backend="xla")
    assert np.array_equal(c.u[:8], a.u)
    assert np.array_equal(c.cost[:8], a.cost)


def test_pack_columns_layout():
    """pack_columns invariants the kernel relies on: homogeneous-row
    sentinel -BIG on unowned columns, +BIG padded vertices with zeroed
    payloads, and shape/placement checks."""
    rng = np.random.default_rng(9)
    table = _synthetic_table(rng, L=5)
    PV, K = 8, 8
    bary, U, V = pallas_eval.pack_columns(table, n_cols=8, PV=PV, K=K)
    assert bary.shape == (PV, K, 8) and U.shape == (PV, 8, 128)
    assert V.shape == (PV, 8)
    p = 2
    # Unowned columns: score at the homogeneous row is -BIG => never
    # win the argmax against any live column.
    assert np.all(bary[:p + 1, p, 5:] == -pallas_eval._BIG)
    # Padded vertices carry +BIG scores (never the min) + zero payload.
    assert np.all(bary[p + 1:, p, :5] == pallas_eval._BIG)
    assert np.all(U[p + 1:] == 0.0) and np.all(V[p + 1:] == 0.0)
    with pytest.raises(ValueError):
        pallas_eval.pack_columns(table, n_cols=4, PV=PV, K=K)
