"""Sharded descent serving (online/sharded.py + parallel.mesh
serving_placement): value parity with the flat descent path, routing
parity, shard balance, and artifact-only (tree-free) construction."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import descent, evaluator, export, sharded
from explicit_hybrid_mpc_tpu.parallel.mesh import serving_placement
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.partition.synthetic import build_synthetic_tree
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def built():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_depth=20)
    res = build_partition(prob, cfg)
    table = export.export_leaves(res.tree)
    dt = descent.export_descent(res.tree, res.roots, table, stage=False)
    return prob, res, table, dt


def test_serving_placement_round_robin():
    devs = jax.devices()
    pl = serving_placement(2 * len(devs))
    assert len(pl) == 2 * len(devs)
    assert pl[: len(devs)] == devs and pl[len(devs):] == devs
    with pytest.raises(ValueError):
        serving_placement(0)


def test_sharded_matches_flat_descent(built, rng):
    prob, res, table, dt = built
    srv = sharded.shard_descent(dt, table, n_shards=4)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(257, 2))
    flat = descent.evaluate_descent(
        jax.tree_util.tree_map(jnp.asarray, dt), evaluator.stage(table),
        jnp.asarray(thetas))
    out = srv.evaluate(thetas)
    np.testing.assert_array_equal(out.inside, np.asarray(flat.inside))
    ok = out.inside
    assert ok.all()
    np.testing.assert_allclose(out.u[ok], np.asarray(flat.u)[ok],
                               atol=1e-8)
    np.testing.assert_allclose(out.cost[ok], np.asarray(flat.cost)[ok],
                               atol=1e-8)
    # Row routing parity, not just values (this partition has no
    # degenerate shared-facet ambiguity at the sampled points).
    rows, nodes = srv.locate(thetas)
    frow, fnode = descent.locate_descent(
        jax.tree_util.tree_map(jnp.asarray, dt), jnp.asarray(thetas))
    np.testing.assert_array_equal(rows, np.asarray(frow))
    np.testing.assert_array_equal(nodes, np.asarray(fnode))
    # Leaf ids are GLOBAL table rows: payload lookups must agree.
    np.testing.assert_array_equal(table.node_id[rows],
                                  np.asarray(fnode))


def test_sharded_outside_flagged(built):
    prob, res, table, dt = built
    srv = sharded.shard_descent(dt, table, n_shards=4)
    out = srv.evaluate(np.asarray([[10.0, 10.0]]))
    assert not bool(out.inside[0])


def test_shards_are_balanced_and_cover(built):
    prob, res, table, dt = built
    srv = sharded.shard_descent(dt, table, n_shards=4)
    sizes = srv.shard_sizes()
    assert sum(sizes) == table.n_leaves
    assert max(sizes) <= 2 * max(1, table.n_leaves // 4)


def test_sharded_from_saved_artifacts(built, tmp_path, rng):
    """The serving path needs only the exported artifacts -- leaf-table
    .npy files (memmap'd) + descent .npz -- never the pickled Tree."""
    import os

    prob, res, table, dt = built
    d = str(tmp_path / "leaves")
    export.write_leaf_table(res.tree, d)
    descent.save_descent(
        descent.export_descent(res.tree, res.roots, table),
        os.path.join(d, "dt.npz"))
    t2 = export.load_leaf_table(d)
    dt2 = descent.load_descent(os.path.join(d, "dt.npz"))
    srv = sharded.shard_descent(dt2, t2, n_shards=3)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(64, 2))
    ref = sharded.shard_descent(dt, table, n_shards=3).evaluate(thetas)
    out = srv.evaluate(thetas)
    np.testing.assert_array_equal(out.u, ref.u)
    np.testing.assert_array_equal(out.leaf, ref.leaf)


def test_sharded_with_kuhn_router(rng):
    """Analytic root routing on a synthetic box tree: same rows as the
    brute-scan server, values matching the flat path."""
    tree, roots = build_synthetic_tree(p=3, depth=6, n_u=2)
    table = export.export_leaves(tree)
    dt = descent.export_descent(tree, roots, table, stage=False)
    router = geometry.kuhn_root_locator(np.zeros(3), np.ones(3))
    thetas = rng.uniform(0.0, 1.0, size=(300, 3))
    srv_scan = sharded.shard_descent(dt, table, n_shards=5)
    srv_router = sharded.shard_descent(dt, table, n_shards=5,
                                       router=router)
    a, b = srv_scan.evaluate(thetas), srv_router.evaluate(thetas)
    np.testing.assert_array_equal(a.leaf, b.leaf)
    np.testing.assert_array_equal(a.u, b.u)
    assert a.inside.all()
    flat = descent.evaluate_descent(
        jax.tree_util.tree_map(jnp.asarray, dt), evaluator.stage(table),
        jnp.asarray(thetas))
    np.testing.assert_allclose(b.u, np.asarray(flat.u), atol=1e-9)


def test_payload_free_shard_flags_outside():
    """A shard covering only payload-free subtrees (fully infeasible
    region) must flag its queries outside with row -1, not crash on
    empty leaf slices."""
    from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree

    t = Tree(p=1, n_u=1)
    r = t.add_root(np.array([[0.0], [1.0]]))
    left, right, i, j, _ = geometry.bisect(t.vertices[r])
    li, ri = t.split(r, left, right, (i, j))
    t.set_leaf(li, LeafData(delta_idx=0, vertex_inputs=np.ones((2, 1)),
                            vertex_costs=np.zeros(2)))
    table = export.export_leaves(t)
    dt = descent.export_descent(t, [r], table, stage=False)
    srv = sharded.shard_descent(dt, table, n_shards=2, granularity=1)
    out = srv.evaluate(np.array([[0.25], [0.75]]))
    assert bool(out.inside[0]) and not bool(out.inside[1])
    assert out.leaf[0] == 0 and out.leaf[1] == -1
    rows, nodes = srv.locate(np.array([[0.75]]))
    assert rows[0] == -1 and nodes[0] == ri
