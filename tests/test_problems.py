import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import make, names


def test_registry():
    assert "double_integrator" in names()
    assert "mass_spring" in names()
    with pytest.raises(KeyError):
        make("nope")


def _rollout_cost(A, B, Q, R, P, x0, us):
    """Brute-force simulation of the MPC objective, independent of
    condense()'s prediction-matrix algebra."""
    x = x0.copy()
    J = 0.0
    for u in us:
        J += 0.5 * x @ Q @ x + 0.5 * u @ R @ u
        x = A @ x + B @ u
    return J + 0.5 * x @ P @ x, x


def test_condense_matches_rollout(rng):
    n, m, N = 3, 2, 4
    A = rng.normal(size=(n, n)) * 0.4 + np.eye(n)
    B = rng.normal(size=(n, m))
    Q = np.eye(n)
    R = np.eye(m) * 0.5
    P = np.eye(n) * 2.0
    sl = base.condense(
        A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(n)] * N,
        Q=Q, R=R, P=P, E=np.eye(n), x_nom=np.zeros(n), n_u=m,
    )
    for _ in range(10):
        theta = rng.normal(size=n)
        z = rng.normal(size=N * m)
        us = z.reshape(N, m)
        J_roll, _ = _rollout_cost(A, B, Q, R, P, theta, us)
        J_can = (0.5 * z @ sl.H @ z + (sl.f + sl.F @ theta) @ z
                 + 0.5 * theta @ sl.Y @ theta + sl.pvec @ theta + sl.cconst)
        assert np.isclose(J_roll, J_can, rtol=1e-10, atol=1e-10)


def test_condense_constraints_match_rollout(rng):
    n, m, N = 2, 1, 3
    A = np.array([[1.0, 0.1], [0.0, 1.0]])
    B = np.array([[0.0], [0.1]])
    Cx, cx = base.box_rows(-np.ones(n), np.ones(n))
    Cu, cu = base.box_rows(-np.ones(m), np.ones(m))
    sl = base.condense(
        A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(n)] * N,
        Q=np.eye(n), R=np.eye(m), P=np.eye(n), E=np.eye(n),
        x_nom=np.zeros(n), n_u=m,
        state_con=[(Cx, cx)] * N, input_con=[(Cu, cu)] * N,
    )
    for _ in range(20):
        theta = rng.uniform(-1, 1, size=n)
        z = rng.uniform(-1.5, 1.5, size=N * m)
        # Constraint satisfaction via canonical rows...
        can_ok = np.all(sl.G @ z <= sl.w + sl.S @ theta + 1e-12)
        # ...equals constraint satisfaction via rollout.
        x = theta.copy()
        roll_ok = True
        for k in range(N):
            u = z[k * m:(k + 1) * m]
            roll_ok &= bool(np.all(np.abs(u) <= 1 + 1e-12))
            x = A @ x + B @ u
            roll_ok &= bool(np.all(np.abs(x) <= 1 + 1e-12))
        assert can_ok == roll_ok


def test_canonical_problems_wellformed():
    for name in names():
        prob = make(name)
        can = prob.canonical
        assert can.H.shape[0] == can.n_delta >= 1
        assert can.G.shape == (can.n_delta, can.nc, can.nz)
        assert can.u_map.shape == (can.n_delta, prob.n_u, can.nz)
        for d in range(can.n_delta):
            eig = np.linalg.eigvalsh(can.H[d])
            assert eig.min() > 0, f"{name}: H[{d}] not PD"


def test_zoh_double_integrator():
    Ac = np.array([[0.0, 1.0], [0.0, 0.0]])
    Bc = np.array([[0.0], [1.0]])
    A, B = base.zoh(Ac, Bc, 0.5)
    np.testing.assert_allclose(A, [[1.0, 0.5], [0.0, 1.0]], atol=1e-12)
    np.testing.assert_allclose(B, [[0.125], [0.5]], atol=1e-12)
