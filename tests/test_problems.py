import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import make, names


def test_registry():
    assert "double_integrator" in names()
    assert "mass_spring" in names()
    with pytest.raises(KeyError):
        make("nope")


def _rollout_cost(A, B, Q, R, P, x0, us):
    """Brute-force simulation of the MPC objective, independent of
    condense()'s prediction-matrix algebra."""
    x = x0.copy()
    J = 0.0
    for u in us:
        J += 0.5 * x @ Q @ x + 0.5 * u @ R @ u
        x = A @ x + B @ u
    return J + 0.5 * x @ P @ x, x


def test_condense_matches_rollout(rng):
    n, m, N = 3, 2, 4
    A = rng.normal(size=(n, n)) * 0.4 + np.eye(n)
    B = rng.normal(size=(n, m))
    Q = np.eye(n)
    R = np.eye(m) * 0.5
    P = np.eye(n) * 2.0
    sl = base.condense(
        A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(n)] * N,
        Q=Q, R=R, P=P, E=np.eye(n), x_nom=np.zeros(n), n_u=m,
    )
    for _ in range(10):
        theta = rng.normal(size=n)
        z = rng.normal(size=N * m)
        us = z.reshape(N, m)
        J_roll, _ = _rollout_cost(A, B, Q, R, P, theta, us)
        J_can = (0.5 * z @ sl.H @ z + (sl.f + sl.F @ theta) @ z
                 + 0.5 * theta @ sl.Y @ theta + sl.pvec @ theta + sl.cconst)
        assert np.isclose(J_roll, J_can, rtol=1e-10, atol=1e-10)


def test_condense_constraints_match_rollout(rng):
    n, m, N = 2, 1, 3
    A = np.array([[1.0, 0.1], [0.0, 1.0]])
    B = np.array([[0.0], [0.1]])
    Cx, cx = base.box_rows(-np.ones(n), np.ones(n))
    Cu, cu = base.box_rows(-np.ones(m), np.ones(m))
    sl = base.condense(
        A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(n)] * N,
        Q=np.eye(n), R=np.eye(m), P=np.eye(n), E=np.eye(n),
        x_nom=np.zeros(n), n_u=m,
        state_con=[(Cx, cx)] * N, input_con=[(Cu, cu)] * N,
    )
    for _ in range(20):
        theta = rng.uniform(-1, 1, size=n)
        z = rng.uniform(-1.5, 1.5, size=N * m)
        # Constraint satisfaction via canonical rows...
        can_ok = np.all(sl.G @ z <= sl.w + sl.S @ theta + 1e-12)
        # ...equals constraint satisfaction via rollout.
        x = theta.copy()
        roll_ok = True
        for k in range(N):
            u = z[k * m:(k + 1) * m]
            roll_ok &= bool(np.all(np.abs(u) <= 1 + 1e-12))
            x = A @ x + B @ u
            roll_ok &= bool(np.all(np.abs(x) <= 1 + 1e-12))
        assert can_ok == roll_ok


def test_prestab_condense_is_exact_substitution(rng):
    """Closed-loop condensing (u = Kx + v) is an exact reparametrization:
    the cost of any input SEQUENCE agrees when expressed in v (J_v(v) =
    J_u(u) with u_k = K x_k + v_k along the closed-loop trajectory),
    constraint satisfaction agrees row-for-row, and the SOLVED problems
    (via the oracle IPM) give the same value function and applied u0."""
    n, m, N = 3, 2, 4
    A = rng.normal(size=(n, n)) * 0.5 + np.eye(n)  # mildly unstable
    B = rng.normal(size=(n, m))
    Q, R, P = np.eye(n), np.eye(m) * 0.5, np.eye(n) * 2.0
    K = -0.3 * np.linalg.pinv(B) @ (A - 0.5 * np.eye(n))
    Cx, cx = base.box_rows(-4 * np.ones(n), 4 * np.ones(n))
    Cu, cu = base.box_rows(-3 * np.ones(m), 3 * np.ones(m))
    kw = dict(A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(n)] * N,
              Q=Q, R=R, P=P, E=np.eye(n), x_nom=np.zeros(n), n_u=m,
              state_con=[(Cx, cx)] * N, input_con=[(Cu, cu)] * N)
    ol = base.condense(**kw)
    cl = base.condense(**kw, K_prestab=K)
    assert cl.u_theta is not None and cl.u_const is not None

    for _ in range(10):
        theta = rng.uniform(-1, 1, size=n)
        v = rng.uniform(-0.5, 0.5, size=N * m)
        # Roll the closed loop to recover the u sequence v encodes.
        x = theta.copy()
        us = []
        for k in range(N):
            u = K @ x + v[k * m:(k + 1) * m]
            us.append(u)
            x = A @ x + B @ u
        z = np.concatenate(us)
        J_v = (0.5 * v @ cl.H @ v + (cl.f + cl.F @ theta) @ v
               + 0.5 * theta @ cl.Y @ theta + cl.pvec @ theta + cl.cconst)
        J_u = (0.5 * z @ ol.H @ z + (ol.f + ol.F @ theta) @ z
               + 0.5 * theta @ ol.Y @ theta + ol.pvec @ theta + ol.cconst)
        assert np.isclose(J_v, J_u, rtol=1e-9, atol=1e-9)
        # Same rows, same satisfaction margins.
        res_v = cl.G @ v - cl.w - cl.S @ theta
        res_u = ol.G @ z - ol.w - ol.S @ theta
        np.testing.assert_allclose(res_v, res_u, atol=1e-9)
        # u0 reconstruction through the affine map.
        u0_v = cl.u_map @ v + cl.u_theta @ theta + cl.u_const
        np.testing.assert_allclose(u0_v, us[0], atol=1e-12)

    # Solved problems agree: same V*(theta) and same applied u0.
    from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle

    class _Wrap(base.HybridMPC):
        name = "_prestab_wrap"

        def __init__(self, sl):
            self._sl = sl
            self.theta_lb = -np.ones(n)
            self.theta_ub = np.ones(n)
            self.n_u = m

        def build_canonical(self):
            return base.stack_slices([self._sl],
                                     deltas=np.zeros((1, 0), np.int64))

    o_ol = Oracle(_Wrap(ol), backend="cpu")
    o_cl = Oracle(_Wrap(cl), backend="cpu")
    thetas = rng.uniform(-0.8, 0.8, size=(8, n))
    s_ol = o_ol.solve_vertices(thetas)
    s_cl = o_cl.solve_vertices(thetas)
    assert s_ol.conv.all() and s_cl.conv.all()
    np.testing.assert_allclose(s_cl.Vstar, s_ol.Vstar, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(s_cl.u0[:, 0], s_ol.u0[:, 0],
                               rtol=1e-5, atol=1e-7)


def test_canonical_problems_wellformed():
    for name in names():
        prob = make(name)
        can = prob.canonical
        assert can.H.shape[0] == can.n_delta >= 1
        assert can.G.shape == (can.n_delta, can.nc, can.nz)
        assert can.u_map.shape == (can.n_delta, prob.n_u, can.nz)
        for d in range(can.n_delta):
            eig = np.linalg.eigvalsh(can.H[d])
            assert eig.min() > 0, f"{name}: H[{d}] not PD"


def test_zoh_double_integrator():
    Ac = np.array([[0.0, 1.0], [0.0, 0.0]])
    Bc = np.array([[0.0], [1.0]])
    A, B = base.zoh(Ac, Bc, 0.5)
    np.testing.assert_allclose(A, [[1.0, 0.5], [0.0, 1.0]], atol=1e-12)
    np.testing.assert_allclose(B, [[0.125], [0.5]], atol=1e-12)
