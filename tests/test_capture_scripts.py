"""Smoke tests for the three benchmark capture scripts (round-2 verdict
item 5: the scripts that carry the round's TPU evidence must be proven
runnable on CPU with tiny budgets BEFORE a chip-up window, the way
tests/test_bench.py proved bench.py after round 1's crash).

Each test runs the script in a subprocess with BENCH_PLATFORM=cpu and
shrunken knobs, then asserts the artifact JSON exists, parses, and has the
fields the judge/BASELINE.md read."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env_extra: dict, out_path: str,
         timeout: int = 420) -> dict:
    env = dict(os.environ, BENCH_PLATFORM="cpu", **env_extra)
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)
    assert os.path.exists(out_path), (
        f"{script} wrote no artifact; stderr tail: {proc.stderr[-800:]}")
    with open(out_path) as f:
        data = json.load(f)
    assert "error" not in data, f"{script} errored: {data['error']}"
    return data


def test_north_star_smoke(tmp_path):
    out = str(tmp_path / "north_star.json")
    data = _run("scripts/north_star.py", {
        "NORTH_STAR_OUT": out,
        "NS_LOG": str(tmp_path / "ns.log.jsonl"),
        "NS_PROBLEM": "double_integrator",
        "NS_TIME_BUDGET": "45",
        "NS_PARITY_EPS": "0.5",
        "NS_POINTS_CAP": "64",
    }, out)
    fl = data["flagship"]
    assert fl["platform"] == "cpu"
    assert fl["regions"] > 0 and fl["regions_per_s"] > 0
    assert fl["vs_serial_estimate"] > 0
    par = data["parity"]
    assert par["parity_ok"] is True, f"parity mismatch: {par}"
    assert par["batched"]["regions"] == par["serial"]["regions"]


def test_bench_configs_smoke(tmp_path):
    out = str(tmp_path / "configs.json")
    data = _run("scripts/bench_configs.py", {
        "CONFIGS_OUT": out,
        "CFG_ONLY": "double_integrator",
        "CFG_TIME_BUDGET": "40",
    }, out)
    assert data["platform"] == "cpu"
    rows = data["rows"]
    assert len(rows) == 1 and rows[0]["problem"] == "double_integrator"
    assert "error" not in rows[0], rows[0]
    assert rows[0]["regions"] > 0
    assert 0.0 < rows[0]["volume_certified_frac"] <= 1.0


def test_precision_check_smoke(tmp_path):
    out = str(tmp_path / "precision.json")
    data = _run("scripts/precision_check.py", {
        "PREC_OUT": out,
        "PREC_PROBLEM": "double_integrator",
        "PREC_POINTS": "16",
        "PREC_EPS": "0.3",
        "PREC_TIME_BUDGET": "90",
    }, out)
    assert data["platform"] == "cpu"
    assert 0.0 <= data["f32_accept_rate"] <= 1.0
    assert data["mixed_kkt"]["converged_frac"] > 0.5
    assert data["f64_kkt"]["converged_frac"] > 0.5
    assert data["parity_valid"] is True
    assert data["mixed_vs_f64_regions_equal"] is True, data["builds"]


def test_online_crossover_smoke(tmp_path):
    out = str(tmp_path / "crossover.json")
    data = _run("scripts/online_crossover.py", {
        "CROSS_OUT": out,
        "CROSS_EPS": "0.5,0.3",
        "CROSS_BATCH": "256",
    }, out)
    assert data["platform"] == "cpu"
    rows = data["rows"]
    assert len(rows) == 2
    for row in rows:
        assert row["leaves"] > 0
        assert row["jax_us"] > 0 and row["descent_us"] > 0
        assert "pallas_us" not in row  # Mosaic timing is TPU-only


def test_tune_schedule_smoke(tmp_path):
    out = str(tmp_path / "tune_schedule.json")
    data = _run("scripts/tune_schedule.py", {
        "TUNE_OUT": out,
        "TUNE_POINTS": "16",
        "TUNE_EPS": "0.5",
        "TUNE_BUILD_BUDGET": "20",
        "TUNE_PROBLEM": "double_integrator",
    }, out, timeout=560)
    assert data["platform"] == "cpu"
    rows = data["schedules"]
    assert len(rows) >= 4
    # Every schedule row (incl. the split point-schedule + rescue ones)
    # must produce timing + convergence + rescue-fraction fields.
    for r in rows:
        assert "error" not in r, r
        assert r["point_us_per_qp"] > 0
        assert 0.0 <= r["converged_frac"] <= 1.0
        assert 0.0 <= r["rescue_frac"] <= 1.0


def test_profile_capture_smoke(tmp_path):
    out = str(tmp_path / "profile.json")
    data = _run("scripts/profile_capture.py", {
        "PROFILE_OUT": out,
        "PROFILE_TRACE_DIR": str(tmp_path / "trace"),
        "PROFILE_PROBLEM": "double_integrator",
        "PROFILE_EPS": "0.5",
        "PROFILE_STEPS": "2",
        "PROFILE_TIME_BUDGET": "60",
    }, out, timeout=420)
    assert data["platform"] == "cpu"


def test_tuned_schedule_env(tmp_path):
    """The watcher derives BENCH_POINT_SCHEDULE / BENCH_RESCUE for later
    captures from a chip-captured tune_schedule.json, and ignores CPU or
    parity-failed recommendations."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from tpu_watch import tuned_schedule_env
    finally:
        sys.path.pop(0)

    p = tmp_path / "tune_schedule.json"

    def write(d):
        p.write_text(json.dumps(d))
        return tuned_schedule_env(str(p))

    good = {"platform": "tpu", "fastest_parity_ok": True,
            "parity_builds": {"fastest": {"schedule": {
                "n_f32": 20, "n_f64": 10, "point": [12, 4],
                "rescue": 30}}}}
    assert write(good) == {"BENCH_POINT_SCHEDULE": "12,4",
                           "BENCH_RESCUE": "30"}
    assert write({**good, "platform": "cpu"}) == {}
    assert write({**good, "fastest_parity_ok": False}) == {}
    # fastest without a point override: nothing the env can express.
    assert write({"platform": "tpu", "fastest_parity_ok": True,
                  "parity_builds": {"fastest": {"schedule": {
                      "n_f32": 16, "n_f64": 6}}}}) == {}
    assert tuned_schedule_env(str(tmp_path / "missing.json")) == {}


def test_precision_check_smoke(tmp_path):
    out = str(tmp_path / "precision.json")
    data = _run("scripts/precision_check.py", {
        "PREC_OUT": out,
        "PREC_PROBLEM": "double_integrator",
        "PREC_EPS": "0.5",
        "PREC_POINTS": "32",
        "PREC_TIME_BUDGET": "60",
        "PREC_SOUND_SAMPLES": "64",
    }, out, timeout=420)
    assert data["platform"] == "cpu"
    assert 0.0 <= data["f32_accept_rate"] <= 1.0
    assert data["builds"]["mixed"]["regions"] > 0
    assert data["builds"]["f64"]["regions"] > 0
    # The guarantee that matters: the mixed tree's own certificates hold
    # at sampled thetas against f64 ground truth.
    snd = data["mixed_sound_sampled"]
    assert snd["n_checked"] > 0
    assert data["mixed_eps_sound"] is True, snd


def test_onset_probe_smoke(tmp_path):
    out = str(tmp_path / "onset.json")
    data = _run("scripts/onset_probe.py", {
        "ONSET_OUT": out,
        "ONSET_FAMILIES": "satellite_z",
        "ONSET_SCALES": "0.5",
        "ONSET_BUDGET": "60",
    }, out, timeout=420)
    assert data["platform"] == "cpu"
    rows = data["families"]["satellite_z"]
    assert len(rows) == 1
    assert rows[0]["regions"] > 0
    assert rows[0]["complete"] in (True, False)
    if rows[0]["complete"]:
        assert rows[0]["projected_full_box_regions"] > rows[0]["regions"]


def test_eps_ladder_smoke(tmp_path):
    out = str(tmp_path / "ladder.json")
    data = _run("scripts/eps_ladder.py", {
        "LADDER_OUT": out,
        "LADDER_PROBLEM": "double_integrator",
        "LADDER_EPS": "0.5,0.2",
        "LADDER_BUDGET": "60",
    }, out, timeout=420)
    assert data["platform"] == "cpu"
    rows = data["rows"]
    assert [r["eps_a"] for r in rows] == [0.5, 0.2]
    assert rows[1]["regions"] > rows[0]["regions"]
    for r in rows:
        assert r["complete"] is True
        assert r["descent_us_per_query"] > 0


def test_maybe_invalidate_bench(tmp_path, monkeypatch):
    """An untuned TPU bench artifact is re-queued exactly once after a
    tuned recommendation lands; tuned or CPU artifacts are left alone."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import tpu_watch
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(tpu_watch, "ART", str(tmp_path))

    def put(name, d):
        with open(tmp_path / name, "w") as f:
            json.dump(d, f)

    tune = {"platform": "tpu", "fastest_parity_ok": True,
            "parity_builds": {"fastest": {"schedule": {
                "point": [12, 4], "rescue": 30}}}}
    put("tune_schedule.json", tune)
    put("bench_tpu.json", {"platform": "tpu", "value": 1.0})
    tpu_watch.maybe_invalidate_bench()
    assert not (tmp_path / "bench_tpu.json").exists()
    assert (tmp_path / "bench_tpu_untuned.json").exists()

    # Tuned artifact (schedule_overrides recorded): never invalidated.
    put("bench_tpu.json", {"platform": "tpu", "value": 2.0,
                           "schedule_overrides": {"point_schedule": [12, 4]}})
    tpu_watch.maybe_invalidate_bench()
    assert (tmp_path / "bench_tpu.json").exists()

    # CPU-fallback artifact: left for the normal needed() re-queue.
    put("bench_tpu.json", {"platform": "cpu", "value": 3.0})
    tpu_watch.maybe_invalidate_bench()
    assert (tmp_path / "bench_tpu.json").exists()
