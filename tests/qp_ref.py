"""Independent dense-QP ground truth for tests: OSQP-style ADMM.

Solves  min 0.5 z'Hz + q'z  s.t.  Gz <= b  with an implementation sharing
no code with the framework's IPM (different algorithm family entirely), so
agreement is meaningful evidence of correctness.  Small/medium problems
only -- this is a test oracle, not a solver.
"""

from __future__ import annotations

import numpy as np


def admm_qp(H, q, G, b, rho: float = 10.0, sigma: float = 1e-6,
            max_iter: int = 50_000, tol: float = 1e-9):
    """Returns (z, obj, converged)."""
    H, q = np.asarray(H, float), np.asarray(q, float)
    G, b = np.asarray(G, float), np.asarray(b, float)
    nz = H.shape[0]
    # Row equilibration of G: ADMM is scaling-sensitive.
    rn = np.maximum(np.linalg.norm(G, axis=1), 1e-12)
    Gs, bs = G / rn[:, None], b / rn
    K = H + sigma * np.eye(nz) + rho * Gs.T @ Gs
    cho = np.linalg.cholesky(K)
    z = np.zeros(nz)
    y = np.minimum(Gs @ z, bs)
    u = np.zeros_like(bs)
    for it in range(max_iter):
        rhs = -q + sigma * z + rho * Gs.T @ (y - u)
        z_new = np.linalg.solve(cho.T, np.linalg.solve(cho, rhs))
        Gz = Gs @ z_new
        y_new = np.minimum(bs, Gz + u)
        u += Gz - y_new
        r_prim = np.max(np.abs(Gz - y_new))
        r_dual = rho * np.max(np.abs(Gs.T @ (y_new - y)))
        z, y = z_new, y_new
        if r_prim < tol and r_dual < tol:
            return z, 0.5 * z @ H @ z + q @ z, True
    return z, 0.5 * z @ H @ z + q @ z, False


def fixed_delta_value(can, d, theta, **kw):
    """V_delta(theta) via ADMM, in the framework's canonical convention
    (theta-cost terms included); None if ADMM fails to converge."""
    q = can.f[d] + can.F[d] @ theta
    b = can.w[d] + can.S[d] @ theta
    z, obj, ok = admm_qp(can.H[d], q, can.G[d], b, **kw)
    if not ok:
        return None
    th = np.asarray(theta, float)
    return (obj + 0.5 * th @ can.Y[d] @ th + can.pvec[d] @ th
            + can.cconst[d])
