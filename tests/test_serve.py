"""Online serving runtime (explicit_hybrid_mpc_tpu/serve/): micro-batch
scheduling under a deadline, hot-swap atomicity across a registry
publish (satellite: concurrent submitters must never observe a torn
cross-version read), degraded-mode fallback causes/counters, the
oversized-batch split in online/sharded.py, the serving health rules,
and the serve_bench closed-loop sweep."""

import json
import os
import sys
import threading
import time
from typing import NamedTuple

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import ServeConfig
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.online import descent, export, sharded
from explicit_hybrid_mpc_tpu.partition.synthetic import build_synthetic_tree
from explicit_hybrid_mpc_tpu.serve import (ControllerRegistry,
                                           FallbackPolicy,
                                           RequestScheduler, root_box,
                                           save_artifacts)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _server(obs=None, scale=1.0, depth=6, max_bucket=None, p=2, n_u=2):
    tree, roots = build_synthetic_tree(p=p, depth=depth, n_u=n_u)
    if scale != 1.0:
        tree._pl_inputs[:] *= scale
        tree._pl_costs[:] *= scale
    table = export.export_leaves(tree)
    dt = descent.export_descent(tree, roots, table, stage=False)
    return sharded.shard_descent(dt, table, n_shards=2, obs=obs,
                                 max_bucket=max_bucket)


@pytest.fixture(scope="module")
def servers():
    """(v1, v2) servers over the same geometry; v2 payloads are exactly
    2x v1's, so v2 results are bitwise 2x v1 results."""
    return _server(), _server(scale=2.0)


# -- scheduler ---------------------------------------------------------------


def test_scheduler_micro_batches_and_heartbeat(servers, rng):
    srv1, _ = servers
    o = obs_lib.Obs("jsonl")
    srv = _server(obs=o)
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", srv)
    with RequestScheduler(reg, "c", max_batch=32, max_wait_us=1500.0,
                          obs=o) as sched:
        thetas = rng.uniform(0, 1, size=(200, 2))
        tickets = [sched.submit(t) for t in thetas]
        results = [t.result(30.0)[0] for t in tickets]
    assert all(r.ok and r.version == "v1" for r in results)
    # Batching actually happened (not 200 single-row batches).
    assert sched.n_batches < 200
    assert sched.n_requests == 200
    # Values match a direct evaluation bit-for-bit.
    ref = srv1.evaluate(thetas)
    for i, r in enumerate(results):
        assert np.array_equal(r.u, ref.u[i])
        assert r.cost == float(ref.cost[i])
    # The serve.eval heartbeat (satellite: obs_watch can alarm on
    # serving stalls) carries the scheduler's queue/fill context.
    evs = [rec for rec in o.sink.records
           if rec.get("name") == "serve.eval"]
    assert evs and all("queue_depth" in e and "batch_fill_frac" in e
                       for e in evs)
    snap = o.metrics.snapshot()
    # Counters: per-controller namespaced + the cross-controller sum.
    assert snap["counters"]["serve.ctl.c.requests"] == 200
    assert snap["counters"]["serve.requests"] == 200
    # Gauges live ONLY under the namespace (a second controller's
    # scheduler must not overwrite them).
    assert snap["gauges"]["serve.ctl.c.p99_us"] > 0
    assert "serve.p99_us" not in snap["gauges"]
    assert "serve.ctl.c.request_s" in snap["histograms"]
    o.close()


def test_scheduler_deadline_flush_single_query(servers):
    """A lone query must not wait for the batch to fill: the deadline
    budget bounds its queue time."""
    srv1, _ = servers
    reg = ControllerRegistry()
    reg.publish("c", "v1", srv1)
    with RequestScheduler(reg, "c", max_batch=256,
                          max_wait_us=2000.0) as sched:
        t0 = time.perf_counter()
        (r,) = sched.submit(np.array([0.3, 0.4])).result(10.0)
        wall = time.perf_counter() - t0
    assert r.ok
    assert wall < 5.0  # flushed on deadline, not on max_batch


def test_scheduler_split_submission_and_close(servers, rng):
    """A submission larger than max_batch spans micro-batches; close()
    drains everything; submit-after-close raises."""
    srv1, _ = servers
    reg = ControllerRegistry()
    reg.publish("c", "v1", srv1)
    sched = RequestScheduler(reg, "c", max_batch=16, max_wait_us=500.0)
    thetas = rng.uniform(0, 1, size=(70, 2))
    t = sched.submit_batch(thetas)
    sched.close()
    results = t.result(1.0)
    assert len(results) == 70 and all(r.ok for r in results)
    ref = srv1.evaluate(thetas)
    assert all(np.array_equal(results[i].u, ref.u[i]) for i in range(70))
    with pytest.raises(RuntimeError):
        sched.submit(thetas[0])


def test_scheduler_validates_knobs(servers):
    srv1, _ = servers
    reg = ControllerRegistry()
    reg.publish("c", "v1", srv1)
    with pytest.raises(ValueError):
        RequestScheduler(reg, "c", max_batch=48)
    with pytest.raises(ValueError):
        RequestScheduler(reg, "c", max_wait_us=0.0)


def test_submit_rejects_wrong_width_without_poisoning_batch(servers,
                                                            rng):
    """A submission whose theta width does not match the controller
    raises ON THE SUBMITTER; co-batched healthy requests from other
    clients still serve (the bad rows never reach the worker's
    np.concatenate)."""
    srv1, _ = servers
    reg = ControllerRegistry()
    reg.publish("c", "v1", srv1)
    with RequestScheduler(reg, "c", max_batch=32,
                          max_wait_us=50_000.0) as sched:
        good = sched.submit_batch(rng.uniform(0, 1, size=(3, 2)))
        with pytest.raises(ValueError, match="parameter dim"):
            sched.submit(np.array([0.1, 0.2, 0.3]))  # p=3 on a p=2 tree
        with pytest.raises(ValueError, match=r"\(k, p\)"):
            sched.submit_batch(np.zeros((2, 2, 2)))
        results = good.result(30.0)
    assert len(results) == 3 and all(r.ok for r in results)


def test_publish_rejects_param_dim_change():
    """The parameter width is a publish-enforced invariant of the
    controller name: rows are width-validated at submit time, so a
    mid-traffic width change would let already-validated queued rows
    reach a later lease's evaluator and fail every co-batched ticket.
    A different-width tree deploys under a NEW controller name."""
    reg = ControllerRegistry()
    reg.publish("c", "v1", _server(p=2))
    assert reg.param_dim("c") == 2
    with pytest.raises(ValueError, match="parameter dim 3"):
        reg.publish("c", "v2", _server(p=3, depth=5))
    assert reg.active_version("c") == "v1"  # rejected: nothing changed
    assert reg.param_dim("c") == 2
    # Same width republishes fine; a new name takes any width.
    reg.publish("c", "v2", _server(p=2, depth=5))
    reg.publish("c3", "v1", _server(p=3, depth=5))
    assert reg.param_dim("c3") == 3


def test_fallback_box_follows_leased_server():
    """The clamp box is re-derived from the server the batch leased,
    so a hot swap to a tree rebuilt on a WIDER box clamps to the new
    certified boundary, not the boot-time one."""
    tree, roots = build_synthetic_tree(p=2, depth=5, n_u=2,
                                       lb=[0.0, 0.0], ub=[2.0, 2.0])
    table = export.export_leaves(tree)
    dt = descent.export_descent(tree, roots, table, stage=False)
    srv2 = sharded.shard_descent(dt, table, n_shards=2)
    # Policy constructed with the ORIGINAL (stale) box.
    fb = FallbackPolicy(np.zeros(2), np.ones(2))
    theta = np.array([[2.5, 0.5]])
    res = srv2.evaluate(theta)
    assert not res.inside[0]
    patched, tags = fb.apply(theta, res, srv2)
    assert tags == ["clamp"] and patched.inside[0]
    # Clamped at srv2's box edge (2.0), not the stale 1.0.
    ref = srv2.evaluate(np.array([[2.0, 0.5]]))
    np.testing.assert_array_equal(patched.u, ref.u)
    # A server without root_bary falls back to the constructor box.
    lb, ub = fb._box(object())
    np.testing.assert_array_equal(lb, np.zeros(2))
    np.testing.assert_array_equal(ub, np.ones(2))


def test_scheduler_flushes_metrics_during_serving(servers, rng,
                                                  monkeypatch):
    """The worker writes metrics snapshots into the stream WHILE
    serving, so the serving health rules alarm live -- not only in the
    close() post-mortem snapshot."""
    from explicit_hybrid_mpc_tpu.serve import scheduler as sched_mod

    monkeypatch.setattr(sched_mod, "METRICS_FLUSH_S", 0.0)
    srv1, _ = servers
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", srv1)
    sched = RequestScheduler(reg, "c", max_batch=8, max_wait_us=500.0,
                             obs=o)
    for t in [sched.submit(th) for th in rng.uniform(0, 1, (40, 2))]:
        t.result(30.0)
    # Snapshot records present BEFORE close, carrying the namespaced
    # serving gauges a live HealthMonitor evaluates.
    snaps = [r for r in o.sink.records if r.get("kind") == "metrics"]
    assert snaps
    assert any("serve.ctl.c.p99_us" in (s.get("gauges") or {})
               for s in snaps)
    mon = HealthMonitor(rules={"serve_p99_us": 1e-6,
                               "min_solves_for_rates": 1.0})
    for rec in o.sink.records:
        mon.feed(rec)
    assert any(e["name"] == "health.serve_p99_us" for e in mon.events)
    sched.close()
    o.close()


# -- registry / hot swap -----------------------------------------------------


def test_registry_publish_lease_retire(servers):
    srv1, srv2 = servers
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    with pytest.raises(KeyError):
        with reg.lease("nope"):
            pass
    v1 = reg.publish("c", "v1", srv1)
    assert reg.active_version("c") == "v1"
    with reg.lease("c") as ver:
        assert ver is v1
        # Swap while leased: v1 retires only after the lease releases.
        reg.publish("c", "v2", srv2)
        assert v1.state == "retiring"
        assert reg.active_version("c") == "v2"
    assert reg.wait_retired(v1, 5.0)
    names = [r["name"] for r in o.sink.records]
    assert names.count("serve.swap") == 2  # initial publish + swap
    assert "serve.retired" in names
    swap = [r for r in o.sink.records if r["name"] == "serve.swap"][-1]
    assert swap["from_version"] == "v1" and swap["to_version"] == "v2"
    o.close()


def test_hot_swap_atomicity_under_concurrent_submits(servers):
    """Satellite acceptance: submitters racing a hot swap observe ONLY
    complete-old-version or complete-new-version results -- never a
    torn read -- and zero requests are dropped.  v2 payloads are
    exactly 2x v1's, so any cross-version mix inside one result is a
    bitwise mismatch against both references."""
    srv1, srv2 = servers
    reg = ControllerRegistry()
    v1 = reg.publish("c", "v1", srv1)
    sched = RequestScheduler(reg, "c", max_batch=32, max_wait_us=800.0)
    stop = threading.Event()
    errors, outs = [], []
    lock = threading.Lock()

    def client(cid):
        r = np.random.default_rng(cid)
        while not stop.is_set():
            th = r.uniform(0, 1, size=(3, 2))  # small-batch submission
            try:
                res = sched.submit_batch(th).result(10.0)
            except Exception as e:  # noqa: BLE001 -- a drop IS a failure
                errors.append(e)
                return
            with lock:
                outs.extend(zip(th, res))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    reg.publish("c", "v2", srv2)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    sched.close()
    assert not errors  # zero dropped requests across the swap
    assert reg.wait_retired(v1, 5.0)  # two-epoch handoff drained
    seen = {r.version for _t, r in outs}
    assert seen == {"v1", "v2"}  # traffic actually straddled the swap
    thetas = np.stack([t for t, _r in outs])
    ref = srv1.evaluate(thetas)
    for k, (_t, r) in enumerate(outs):
        scale = 1.0 if r.version == "v1" else 2.0
        assert np.array_equal(r.u, scale * ref.u[k]), (k, r.version)
        assert r.cost == scale * float(ref.cost[k])


# -- fallback ----------------------------------------------------------------


def test_fallback_clamp_outside_box(servers):
    srv1, _ = servers
    o = obs_lib.Obs("jsonl")
    lb, ub = root_box(srv1)
    np.testing.assert_allclose(lb, 0.0, atol=1e-12)
    np.testing.assert_allclose(ub, 1.0, atol=1e-12)
    fb = FallbackPolicy(lb, ub, obs=o)
    thetas = np.array([[0.5, 0.5], [1.4, 0.5], [-0.2, 2.0]])
    res = srv1.evaluate(thetas)
    assert list(res.inside) == [True, False, False]
    patched, tags = fb.apply(thetas, res, srv1)
    assert tags == [None, "clamp", "clamp"]
    assert patched.inside.all()
    # Clamped rows carry the law of the nearest certified leaf,
    # evaluated at the clamped coordinate.
    ref = srv1.evaluate(np.clip(thetas, lb, ub))
    np.testing.assert_array_equal(patched.u, ref.u)
    c = o.metrics.snapshot()["counters"]
    assert c["serve.fallback.outside_box"] == 2
    assert c["serve.fallback.clamp"] == 2
    assert c["serve.fallback.requests"] == 2
    assert c.get("serve.fallback.hole", 0) == 0
    o.close()


class _FakeSol(NamedTuple):
    dstar: np.ndarray
    u0: np.ndarray
    Vstar: np.ndarray


class _FakeOracle:
    """Minimal solve_vertices stand-in: one commutation, u = 7*theta."""

    def __init__(self):
        self.n_calls = 0

    def solve_vertices(self, thetas):
        self.n_calls += thetas.shape[0]
        K = thetas.shape[0]
        u0 = 7.0 * thetas[:, None, :]
        return _FakeSol(dstar=np.zeros(K, dtype=np.int64), u0=u0,
                        Vstar=thetas.sum(axis=1))


def test_fallback_hole_routes_to_budgeted_oracle():
    """In-box queries landing on a payload-free (hole) leaf cannot be
    clamp-served; the oracle path answers a bounded fraction and the
    rest are counted unserved."""
    from explicit_hybrid_mpc_tpu.partition import geometry
    from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree

    t = Tree(p=1, n_u=1)
    r = t.add_root(np.array([[0.0], [1.0]]))
    left, right, i, j, _ = geometry.bisect(t.vertices[r])
    li, ri = t.split(r, left, right, (i, j))
    t.set_leaf(li, LeafData(delta_idx=0, vertex_inputs=np.ones((2, 1)),
                            vertex_costs=np.zeros(2)))
    table = export.export_leaves(t)
    dt = descent.export_descent(t, [r], table, stage=False)
    srv = sharded.shard_descent(dt, table, n_shards=2, granularity=1)
    o = obs_lib.Obs("jsonl")
    oracle = _FakeOracle()
    fb = FallbackPolicy(np.zeros(1), np.ones(1), oracle=oracle,
                        max_oracle_frac=0.5, obs=o)
    # 4 requests: 2 in the certified half, 2 in the hole; budget
    # 0.5 * 4 = 2 oracle solves -> both holes served this round.
    thetas = np.array([[0.25], [0.30], [0.75], [0.80]])
    res = srv.evaluate(thetas)
    patched, tags = fb.apply(thetas, res, srv)
    assert tags[:2] == [None, None]
    assert tags[2:] == ["oracle", "oracle"]
    np.testing.assert_allclose(patched.u[2:], 7.0 * thetas[2:])
    assert patched.inside.all()
    c = o.metrics.snapshot()["counters"]
    assert c["serve.fallback.hole"] == 2
    assert c["serve.fallback.oracle"] == 2
    # Budget exhausted: the next hole burst degrades to unserved.
    thetas2 = np.array([[0.9], [0.95], [0.85]])
    res2 = srv.evaluate(thetas2)
    patched2, tags2 = fb.apply(thetas2, res2, srv)
    assert tags2.count("unserved") >= 2  # budget allows at most 1 more
    assert oracle.n_calls <= int(0.5 * fb.n_seen)
    o.close()


class _MissOracle:
    """solve_vertices stand-in that finds NO valid commutation."""

    def solve_vertices(self, thetas):
        K = thetas.shape[0]
        return _FakeSol(dstar=np.full(K, -1, dtype=np.int64),
                        u0=np.full((K, 1, thetas.shape[1]), np.nan),
                        Vstar=np.full(K, np.inf))


def test_fallback_oracle_miss_leaves_row_untouched():
    """'unserved' means UNTOUCHED: an oracle miss (dstar=-1) must not
    overwrite the raw row with an invalid commutation's u and a +inf
    cost (which would also break strict-JSON result consumers)."""
    from explicit_hybrid_mpc_tpu.partition import geometry
    from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree

    t = Tree(p=1, n_u=1)
    r = t.add_root(np.array([[0.0], [1.0]]))
    left, right, i, j, _ = geometry.bisect(t.vertices[r])
    li, ri = t.split(r, left, right, (i, j))
    t.set_leaf(li, LeafData(delta_idx=0, vertex_inputs=np.ones((2, 1)),
                            vertex_costs=np.zeros(2)))
    table = export.export_leaves(t)
    dt = descent.export_descent(t, [r], table, stage=False)
    srv = sharded.shard_descent(dt, table, n_shards=2, granularity=1)
    fb = FallbackPolicy(np.zeros(1), np.ones(1), oracle=_MissOracle(),
                        max_oracle_frac=1.0)
    thetas = np.array([[0.75]])  # the hole half
    res = srv.evaluate(thetas)
    assert not res.inside[0]
    patched, tags = fb.apply(thetas, res, srv)
    assert tags == ["unserved"]
    np.testing.assert_array_equal(patched.u, res.u)  # raw, not NaN
    np.testing.assert_array_equal(patched.cost, res.cost)  # not +inf
    assert not patched.inside[0]


def test_fallback_off_mode_passthrough(servers):
    srv1, _ = servers
    fb = FallbackPolicy(np.zeros(2), np.ones(2), mode="off")
    thetas = np.array([[2.0, 2.0]])
    res = srv1.evaluate(thetas)
    patched, tags = fb.apply(thetas, res, srv1)
    assert tags == [None] and not patched.inside[0]
    with pytest.raises(ValueError):
        FallbackPolicy(np.zeros(2), np.ones(2), mode="nearest")


# -- oversized batches (online/sharded.py satellite) -------------------------


def test_oversized_batch_splits_and_reports(rng):
    """A batch beyond max_bucket is split to the max bucket -- results
    identical to the uncapped server -- and a health.oversized_batch
    event (adopted by HealthMonitor) replaces the silent fresh
    compile."""
    o = obs_lib.Obs("jsonl")
    srv_cap = _server(obs=o, max_bucket=16)
    srv_ref = _server()
    thetas = rng.uniform(0, 1, size=(70, 2))
    out = srv_cap.evaluate(thetas)
    ref = srv_ref.evaluate(thetas)
    np.testing.assert_array_equal(out.u, ref.u)
    np.testing.assert_array_equal(out.leaf, ref.leaf)
    np.testing.assert_array_equal(out.inside, ref.inside)
    rows_c, nodes_c = srv_cap.locate(thetas)
    rows_r, nodes_r = srv_ref.locate(thetas)
    np.testing.assert_array_equal(rows_c, rows_r)
    np.testing.assert_array_equal(nodes_c, nodes_r)
    evs = [r for r in o.sink.records
           if r.get("name") == "health.oversized_batch"]
    assert len(evs) == 2  # one per oversized call (evaluate + locate)
    assert evs[0]["severity"] == "warn"
    assert evs[0]["value"] == 70 and evs[0]["threshold"] == 16
    assert o.metrics.snapshot()["counters"][
        "serve.oversized_batches"] == 2
    # The monitor adopts the event: an external tailer exits nonzero.
    mon = HealthMonitor()
    for rec in o.sink.records:
        mon.feed(rec)
    assert mon.worst == "warn"
    with pytest.raises(ValueError):
        _server(max_bucket=24)  # non-pow-2 cap rejected
    o.close()


# -- serving health rules ----------------------------------------------------


def test_health_serve_rules_fire_on_gauges():
    mon = HealthMonitor(rules={"serve_p99_us": 5000.0,
                               "fallback_frac": 0.1,
                               "min_solves_for_rates": 10.0})
    rec = {"kind": "metrics", "name": "snapshot",
           "counters": {"serve.requests": 50},
           "gauges": {"serve.p99_us": 9000.0,
                      "serve.fallback_frac": 0.3}}
    evs = mon.feed(rec)
    names = {e["name"] for e in evs}
    assert names == {"health.serve_p99_us", "health.fallback_frac"}
    assert mon.worst == "warn"
    # Volume-gated: the same gauges under min volume stay silent.
    mon2 = HealthMonitor(rules={"serve_p99_us": 5000.0,
                                "fallback_frac": 0.1,
                                "min_solves_for_rates": 100.0})
    assert mon2.feed(rec) == []
    # Disabled (0) thresholds never fire.
    mon3 = HealthMonitor(rules={"serve_p99_us": 0.0,
                                "fallback_frac": 0.0,
                                "min_solves_for_rates": 10.0})
    assert mon3.feed(rec) == []


def test_health_serve_rules_per_controller_no_masking():
    """Two controllers on one obs handle: the healthy one's gauges must
    not mask the breaching one's (the scheduler namespaces its gauges
    serve.ctl.<name>.*; the rules scan every controller and volume-gate
    each on ITS OWN request counter)."""
    mon = HealthMonitor(rules={"serve_p99_us": 5000.0,
                               "fallback_frac": 0.1,
                               "min_solves_for_rates": 10.0})
    rec = {"kind": "metrics", "name": "snapshot",
           "counters": {"serve.ctl.good.requests": 500,
                        "serve.ctl.bad.requests": 500,
                        "serve.ctl.tiny.requests": 3},
           "gauges": {"serve.ctl.good.p99_us": 800.0,
                      "serve.ctl.good.fallback_frac": 0.01,
                      "serve.ctl.bad.p99_us": 50_000.0,
                      "serve.ctl.bad.fallback_frac": 0.4,
                      # Breaching gauges but 3 requests: volume-gated.
                      "serve.ctl.tiny.p99_us": 90_000.0}}
    evs = mon.feed(rec)
    assert {e["name"] for e in evs} == {"health.serve_p99_us",
                                        "health.fallback_frac"}
    assert all("'bad'" in e["msg"] for e in evs)
    # A second breaching controller fires its own event -- the first
    # one's cooldown is per-controller, not per-rule.
    rec2 = {"kind": "metrics", "name": "snapshot",
            "counters": {"serve.ctl.bad.requests": 600,
                         "serve.ctl.worse.requests": 600},
            "gauges": {"serve.ctl.bad.p99_us": 50_000.0,
                       "serve.ctl.worse.p99_us": 70_000.0}}
    evs2 = mon.feed(rec2)
    assert [e for e in evs2 if "'worse'" in e["msg"]]
    assert not [e for e in evs2 if "'bad'" in e["msg"]]  # cooling down


def test_serve_config_validation():
    ServeConfig()  # defaults valid
    with pytest.raises(ValueError):
        ServeConfig(max_batch=100)
    with pytest.raises(ValueError):
        ServeConfig(max_bucket=128, max_batch=256)
    # max_bucket unset still validates against the EVALUATOR default:
    # a max_batch above it would make every full micro-batch split.
    from explicit_hybrid_mpc_tpu.config import DEFAULT_MAX_BUCKET
    with pytest.raises(ValueError, match="effective"):
        ServeConfig(max_batch=DEFAULT_MAX_BUCKET * 2)
    ServeConfig(max_batch=DEFAULT_MAX_BUCKET)  # at the cap: valid
    with pytest.raises(ValueError):
        ServeConfig(max_wait_us=0)
    with pytest.raises(ValueError):
        ServeConfig(fallback="nearest")
    with pytest.raises(ValueError):
        ServeConfig(max_oracle_frac=1.5)
    with pytest.raises(ValueError):
        ServeConfig(obs="loud")


# -- serve_bench + CLI -------------------------------------------------------


def test_serve_bench_sweep_with_hot_swap(tmp_path, monkeypatch):
    """Acceptance: the closed-loop sweep reports p99, sustains
    batch-fill >= 0.5 at the top offered rate, and the mid-run hot
    swap drops zero requests with bit-identical per-version results;
    the condensed serve_* row lands in the (test-scoped) history."""
    monkeypatch.setenv("SERVE_BENCH_SECS", "0.5")
    monkeypatch.setenv("SERVE_BENCH_DEPTH", "5")
    monkeypatch.setenv("SERVE_BENCH_RATES", "800,6000")
    monkeypatch.setenv("SERVE_BENCH_MAX_BATCH", "32")
    hist = tmp_path / "hist.jsonl"
    monkeypatch.setenv("BENCH_HISTORY", str(hist))
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_gate
        import serve_bench

        result = serve_bench.run(out_path=str(tmp_path / "serve.json"))
        assert result["serve_p99_us"] is not None
        assert result["serve_batch_fill"] >= 0.5
        assert result["swap_dropped"] == 0
        assert result["swap_torn"] == 0
        assert result["swap_drained"] is True
        assert result["versions_seen"] == ["v1", "v2"]
        assert all(r["p99_us"] is not None for r in result["rates"])
        rows = bench_gate.load_history(str(hist))
        assert len(rows) == 1
        row = rows[0]
        # Serve rows gate their own metric family: serve_* present,
        # the build-family headline "value" absent (window isolation).
        assert row["serve_p99_us"] == result["serve_p99_us"]
        assert row["fallback_frac"] == result["fallback_frac"]
        assert row["value"] is None
        assert row["swap_dropped"] == 0
        flags, _info = bench_gate.gate(row, rows)
        assert flags == []  # candidate vs itself-excluded base: clean
    finally:
        sys.path.pop(0)


def test_serve_cli_selftest(tmp_path, capsys):
    """main.py `serve` subcommand: deploy from saved artifacts, run the
    selftest loop, emit one JSON summary."""
    from explicit_hybrid_mpc_tpu.main import main as cli_main

    tree, roots = build_synthetic_tree(p=2, depth=5, n_u=1)
    d = str(tmp_path / "artifacts")
    save_artifacts(tree, roots, d)
    rc = cli_main(["serve", "--artifacts", d, "--controller", "t",
                   "--shards", "2", "--max-batch", "16",
                   "--selftest", "64"])
    assert rc == 0
    summ = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summ["requests"] == 64
    assert summ["controller"] == "t" and summ["version"] == "v1"
    assert summ["p99_us"] is not None
    assert summ["fallback_served"] > 0  # the outside band was exercised
